"""nodetool: operator commands over a node/engine.

Reference counterpart: tools/nodetool/ (161 JMX subcommands over
NodeProbe). This framework exposes the same operations as direct Python
API on the Node/StorageEngine (the JMX transport is replaced by in-process
calls; a remote admin protocol can wrap these functions); `python -m
cassandra_tpu.tools.nodetool <cmd> --data <dir>` drives a local engine.

Implemented commands: status, info, flush, compact, compactionstats,
tablestats, repair, cleanup, gettraces? (tracing via session), ring.
"""
from __future__ import annotations

import argparse
import json
import sys


def status(node) -> list[dict]:
    """nodetool status: per-endpoint liveness + ownership."""
    out = []
    for ep, toks in node.ring.endpoints.items():
        out.append({"endpoint": ep.name, "dc": ep.dc, "rack": ep.rack,
                    "status": "UN" if node.is_alive(ep) else "DN",
                    "tokens": len(toks)})
    return out


def info(engine) -> dict:
    """nodetool info: storage totals."""
    tables = {}
    for cfs in engine.stores.values():
        tables[cfs.table.full_name()] = {
            "sstables": len(cfs.live_sstables()),
            "memtable_cells": len(cfs.memtable),
            "disk_bytes": sum(s.size_bytes for s in cfs.live_sstables()),
        }
    return {"tables": tables}


def flush(engine, keyspace: str | None = None,
          table: str | None = None) -> int:
    n = 0
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        if cfs.flush() is not None:
            n += 1
    return n


def compact(engine, keyspace: str | None = None,
            table: str | None = None) -> list[dict]:
    """nodetool compact: major compaction."""
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        stats = engine.compactions.major_compaction(cfs)
        if stats is not None:
            out.append(stats)
    return out


def compactionstats(engine) -> list[dict]:
    out = []
    for cfs in engine.stores.values():
        out.extend(cfs.compaction_history)
    return out


def tablestats(engine, keyspace: str | None = None) -> dict:
    out = {}
    for cfs in engine.stores.values():
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        live = cfs.live_sstables()
        out[t.full_name()] = {
            "sstable_count": len(live),
            "space_used_bytes": sum(s.size_bytes for s in live),
            "cells": sum(s.n_cells for s in live),
            "partitions_estimate": sum(s.n_partitions for s in live),
            "tombstones": sum(s.n_tombstones for s in live),
            "memtable_cells": len(cfs.memtable),
            "reads": cfs.metrics["reads"],
            "writes": cfs.metrics["writes"],
            "flushes": cfs.metrics["flushes"],
            "row_cache": (None if cfs.row_cache is None
                          else {"hits": cfs.row_cache.hits,
                                "misses": cfs.row_cache.misses,
                                "entries": len(cfs.row_cache)}),
        }
    return out


def repair(node, keyspace: str, table: str | None = None,
           full: bool = False) -> list[dict]:
    """nodetool repair — incremental by default: validation still covers
    the FULL data set (unrepaired-only trees diverge once repaired
    status differs across replicas), but afterwards the validated
    unrepaired sstables are ANTICOMPACTED and stamped repairedAt so the
    compaction split applies; --full skips the stamping entirely."""
    out = []
    ks = node.schema.keyspaces[keyspace]
    for name in ([table] if table else list(ks.tables)):
        out.append({"table": f"{keyspace}.{name}",
                    **node.repair.repair_table(keyspace, name,
                                               incremental=not full)})
    return out


def cleanup(node, keyspace: str | None = None,
            table: str | None = None) -> list[dict]:
    """nodetool cleanup: rewrite sstables dropping cells for token
    ranges this node no longer replicates (post-bootstrap/move data
    reclamation — CompactionManager.performCleanup role)."""
    import numpy as np

    from ..cluster.replication import ReplicationStrategy
    from ..storage.cellbatch import (CellBatch, batch_tokens,
                                     token_range_mask)
    from ..storage.rewrite import rewrite_sstable
    out = []
    engine = node.engine
    for cfs in list(engine.stores.values()):
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        if table and t.name != table:
            continue
        ksm = node.schema.keyspaces.get(t.keyspace)
        if ksm is None:
            continue
        strat = ReplicationStrategy.create(ksm.params.replication)
        owned = []
        for lo, hi in node.ring.all_ranges():
            if node.endpoint in strat.replicas(node.ring, hi):
                if lo == hi:               # single-token ring: the one
                    owned.append((-(1 << 63), (1 << 63) - 1))  # arc IS
                elif lo <= hi:                         # the full ring
                    owned.append((lo, hi))
                else:                      # wrap arc
                    owned.append((-(1 << 63), hi))
                    owned.append((lo, (1 << 63) - 1))
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                segs = list(sst.scanner())
                if not segs:
                    continue
                cat = CellBatch.concat(segs)
                cat.sorted = True
                keep = token_range_mask(batch_tokens(cat), owned)
                dropped = int((~keep).sum())
                if dropped == 0:
                    continue

                def fill(w, cat=cat, keep=keep):
                    idx = np.flatnonzero(keep)
                    if len(idx):
                        part = cat.apply_permutation(idx)
                        part.sorted = True
                        w.append(part)

                rewrite_sstable(cfs, sst,
                                [(sst.repaired_at, sst.level, fill)])
                out.append({"table": t.full_name(),
                            "generation": sst.desc.generation,
                            "cells_dropped": dropped})
    return out


def getendpoints(node, keyspace: str, table: str, key: str) -> list[str]:
    """nodetool getendpoints: replicas for a partition key. Values are
    converted by the COLUMN TYPE (never guessed from the text — a text
    key '7' must not tokenize as an int), and composite partition keys
    take ':'-separated components so the token matches the write path's
    composite framing."""
    from ..cluster.replication import ReplicationStrategy
    from .copyutil import _parse_value
    t = node.schema.get_table(keyspace, table)
    cols = t.partition_key_columns
    parts = key.split(":") if len(cols) > 1 else [key]
    if len(parts) != len(cols):
        raise ValueError(
            f"partition key of {keyspace}.{table} has {len(cols)} "
            f"components ({', '.join(c.name for c in cols)}); pass them "
            "':'-separated")
    vals = [_parse_value(p, c.cql_type) for p, c in zip(parts, cols)]
    pk = t.serialize_partition_key(vals)
    strat = ReplicationStrategy.create(
        node.schema.keyspaces[keyspace].params.replication)
    return [e.name for e in strat.replicas(node.ring,
                                           node.ring.token_of(pk))]


def gossipinfo(node) -> dict:
    """nodetool gossipinfo."""
    out = {}
    for ep, st in node.gossiper.states.items():
        out[ep.name] = {"generation": st.generation,
                        "version": st.version,
                        "alive": bool(st.alive),
                        "app_states": dict(st.app_states)}
    return out


def version(engine=None) -> dict:
    """nodetool version."""
    return {"release": "cassandra-tpu 2.0", "cql": "3.4.5",
            "sstable_format": "ctpu/ca"}


def describecluster(node) -> dict:
    """nodetool describecluster."""
    return {
        "name": "cassandra_tpu",
        "partitioner": "Murmur3Partitioner",
        "endpoints": [e.name for e in node.ring.endpoints],
        "schema_epoch": getattr(getattr(node, "schema_sync", None),
                                "epoch", None),
        # topology rides the same epoch log (TCM): the metadata epoch IS
        # the schema_sync epoch; kept as a separate key for operators
        "metadata_epoch": getattr(getattr(node, "schema_sync", None),
                                  "epoch", None),
        "pending_joins": [e.name for e in node.ring.pending],
        "replacing": {n.name: d.name
                      for n, d in node.ring.replacing.items()},
    }


def setcompactionthroughput(engine, mib_s: int) -> dict:
    """nodetool setcompactionthroughput (0 = unthrottled). Applies to
    the engine's background CompactionManager (wired at engine init;
    daemons run its worker via enable_auto)."""
    engine.compactions.limiter.rate = mib_s * 2**20
    return {"compaction_throughput_mib": mib_s}


def getcompactionthroughput(engine) -> dict:
    """nodetool getcompactionthroughput."""
    return {"compaction_throughput_mib":
            int(engine.compactions.limiter.rate // 2**20)}


def setslowquerythreshold(engine, ms: float) -> dict:
    """slow_query_log_timeout_in_ms knob (db/monitoring role)."""
    engine.monitor.threshold_ms = float(ms)
    return {"slow_query_threshold_ms": float(ms)}


def upgradesstables(engine, keyspace: str | None = None,
                    table: str | None = None) -> list[dict]:
    """nodetool upgradesstables: rewrite every sstable in the current
    format (compaction/Upgrader role — after a format revision, old
    generations are re-serialized through the current writer)."""
    from ..storage.rewrite import rewrite_sstable
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                def fill(w, sst=sst):
                    for i in range(sst.n_segments):
                        w.append(sst._read_segment(i))

                new = rewrite_sstable(
                    cfs, sst, [(sst.repaired_at, sst.level, fill)])
                out.append({"table": cfs.table.full_name(),
                            "from_generation": sst.desc.generation,
                            "to_generation":
                                new[0].desc.generation if new else None})
    return out


def sstablesplit(engine, keyspace: str, table: str,
                 target_mib: int = 50) -> list[dict]:
    """SSTableSplitter role: carve an oversized sstable into ~target
    sized outputs, split at partition boundaries."""
    import numpy as np

    from ..storage.cellbatch import CellBatch
    from ..storage.rewrite import rewrite_sstable
    cfs = engine.store(keyspace, table)
    target = max(1, target_mib * 2**20)
    out = []
    with engine.compactions.cfs_lock(cfs):
        for sst in list(cfs.live_sstables()):
            if sst.data_size <= target:
                continue
            n_parts = min(64, max(2, -(-sst.data_size // target)))
            segs = list(sst.scanner())
            if not segs:
                continue
            cat = CellBatch.concat(segs)
            cat.sorted = True
            # partition boundaries: first cell of each partition (the
            # token+pkh lanes change)
            keys = cat.lanes[:, 0].astype(np.uint64) << np.uint64(32) \
                | cat.lanes[:, 1]
            starts = np.flatnonzero(np.diff(keys) != 0) + 1
            cuts = [0]
            for p in range(1, n_parts):
                want = p * len(cat) // n_parts
                j = int(np.searchsorted(starts, want))
                cut = int(starts[j]) if j < len(starts) else len(cat)
                if cut > cuts[-1]:
                    cuts.append(cut)
            cuts.append(len(cat))

            def fill_for(lo, hi, cat=cat):
                def fill(w):
                    part = cat.slice_range(lo, hi)
                    part.sorted = True
                    w.append(part)
                return fill

            parts = [(sst.repaired_at, sst.level, fill_for(lo, hi))
                     for lo, hi in zip(cuts, cuts[1:]) if hi > lo]
            new = rewrite_sstable(cfs, sst, parts)
            out.append({"table": cfs.table.full_name(),
                        "generation": sst.desc.generation,
                        "outputs": [r.desc.generation for r in new]})
    return out


def ring(node) -> list[dict]:
    out = []
    for ep, toks in sorted(node.ring.endpoints.items(),
                           key=lambda kv: kv[0].name):
        for t in sorted(toks):
            out.append({"token": t, "endpoint": ep.name})
    return out


def snapshot(engine, keyspace: str | None = None,
             table: str | None = None, tag: str | None = None) -> list[str]:
    """nodetool snapshot."""
    from ..storage import snapshot as snap
    out = []
    for cfs in engine.stores.values():
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        cfs.flush()   # snapshots must include memtable contents
        out.append(f"{cfs.table.full_name()}:{snap.snapshot(cfs, tag)}")
    return out


def listsnapshots(engine) -> list[dict]:
    from ..storage import snapshot as snap
    out = []
    for cfs in engine.stores.values():
        out.extend(snap.list_snapshots(cfs))
    return out


def clearsnapshot(engine, tag: str | None = None) -> int:
    from ..storage import snapshot as snap
    return sum(snap.clear_snapshot(cfs, tag)
               for cfs in engine.stores.values())


def scrub(engine, keyspace: str | None = None,
          table: str | None = None) -> list[dict]:
    """nodetool scrub: rewrite each sstable keeping every readable
    segment, dropping corrupt ones (io/sstable/format/
    SortedTableScrubber role). The unreadable cells are gone either way;
    scrub turns a read-aborting sstable into a clean one."""
    from ..storage.rewrite import rewrite_sstable
    from ..storage.sstable.reader import CorruptSSTableError
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                counts = {"kept": 0, "dropped": 0}

                def fill(w, sst=sst, counts=counts):
                    for i in range(sst.n_segments):
                        try:
                            seg = sst._read_segment(i)
                        except CorruptSSTableError:
                            counts["dropped"] += 1
                            continue
                        w.append(seg)
                        counts["kept"] += 1

                rewrite_sstable(cfs, sst,
                                [(sst.repaired_at, sst.level, fill)])
                out.append({"table": cfs.table.full_name(),
                            "generation": sst.desc.generation,
                            "segments_kept": counts["kept"],
                            "segments_dropped": counts["dropped"]})
    return out


def garbagecollect(engine, keyspace: str | None = None,
                   table: str | None = None) -> list[dict]:
    """Single-sstable rewrite dropping gc-able tombstones
    (nodetool garbagecollect)."""
    from ..compaction.task import CompactionTask
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        with engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                out.append(CompactionTask(cfs, [sst]).execute())
    return out


def main(argv=None):
    p = argparse.ArgumentParser(prog="nodetool")
    p.add_argument("command", choices=["info", "flush", "compact",
                                       "compactionstats", "tablestats",
                                       "garbagecollect", "scrub"])
    p.add_argument("--data", required=True, help="data directory")
    p.add_argument("--keyspace")
    p.add_argument("--table")
    args = p.parse_args(argv)

    from ..schema import Schema
    from ..storage.engine import StorageEngine
    engine = StorageEngine(args.data, Schema())
    fn = globals()[args.command]
    import inspect
    kwargs = {}
    sig = inspect.signature(fn)
    if "keyspace" in sig.parameters:
        kwargs["keyspace"] = args.keyspace
    if "table" in sig.parameters:
        kwargs["table"] = args.table
    print(json.dumps(fn(engine, **kwargs), indent=2, default=str))
    engine.close()


if __name__ == "__main__":
    main()
