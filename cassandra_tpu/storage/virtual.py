"""Virtual tables: in-memory system tables served through the read path.

Reference counterpart: db/virtual/ (AbstractVirtualTable + 40 tables:
settings, clients, caches, sstable_tasks, ...) plus the classic
system.local / system.peers. A virtual table supplies row dicts on demand;
the CQL executor projects them like ordinary rows.
"""
from __future__ import annotations

from ..schema import TableMetadata, make_table


def _snapshot(seq):
    """Copy a concurrently-appended deque/list for safe iteration; an
    append racing the copy raises RuntimeError — retry once, then serve
    what a best-effort copy yields."""
    for _ in range(3):
        try:
            return list(seq)
        except RuntimeError:
            continue
    return []


class VirtualTable:
    def __init__(self, table: TableMetadata, rows_fn):
        self.table = table
        self.rows_fn = rows_fn

    def rows(self) -> list[dict]:
        return list(self.rows_fn())


class VirtualSchema:
    """Registry of virtual keyspaces/tables for one backend."""

    def __init__(self):
        self.tables: dict[tuple[str, str], VirtualTable] = {}

    def register(self, vt: VirtualTable) -> None:
        self.tables[(vt.table.keyspace, vt.table.name)] = vt

    def get(self, keyspace: str, name: str) -> VirtualTable | None:
        return self.tables.get((keyspace, name))


def build_engine_virtuals(engine) -> VirtualSchema:
    """system/system_views tables over a local StorageEngine."""
    vs = VirtualSchema()

    t_local = make_table("system", "local", pk=["key"],
                         cols={"key": "text", "cluster_name": "text",
                               "release_version": "text",
                               "partitioner": "text"})
    vs.register(VirtualTable(t_local, lambda: [{
        "key": "local", "cluster_name": "cassandra_tpu",
        "release_version": "0.1.0",
        "partitioner": "Murmur3Partitioner"}]))

    t_sst = make_table("system_views", "sstables", pk=["keyspace_name"],
                       ck=["table_name", "generation"],
                       cols={"keyspace_name": "text", "table_name": "text",
                             "generation": "int", "cells": "bigint",
                             "partitions": "bigint", "size_bytes": "bigint",
                             "level": "int", "tombstones": "bigint"})

    def sstable_rows():
        for cfs in engine.stores.values():
            for s in cfs.live_sstables():
                yield {"keyspace_name": cfs.table.keyspace,
                       "table_name": cfs.table.name,
                       "generation": s.desc.generation,
                       "cells": s.n_cells, "partitions": s.n_partitions,
                       "size_bytes": s.data_size, "level": s.level,
                       "tombstones": s.n_tombstones}
    vs.register(VirtualTable(t_sst, sstable_rows))

    t_ch = make_table("system_views", "compaction_history", pk=["id"],
                      cols={"id": "int", "keyspace_name": "text",
                            "table_name": "text", "cells_read": "bigint",
                            "cells_written": "bigint",
                            "bytes_read": "bigint",
                            "bytes_written": "bigint", "seconds": "double"})

    def history_rows():
        i = 0
        for cfs in engine.stores.values():
            # deque (bounded ring): a background compaction appending
            # mid-iteration would raise RuntimeError — copy first
            for st in _snapshot(cfs.compaction_history):
                yield {"id": i, "keyspace_name": cfs.table.keyspace,
                       "table_name": cfs.table.name,
                       "cells_read": st["cells_read"],
                       "cells_written": st["cells_written"],
                       "bytes_read": st["bytes_read"],
                       "bytes_written": st["bytes_written"],
                       "seconds": st["seconds"]}
                i += 1
    vs.register(VirtualTable(t_ch, history_rows))

    # --- compactions_in_progress (db/virtual/SSTableTasksTable +
    # ActiveCompactions): live per-task progress while compactor slots
    # run — phase, bytes read/written, % done, ETA
    t_cip = make_table(
        "system_views", "compactions_in_progress", pk=["id"],
        cols={"id": "int", "keyspace_name": "text", "table_name": "text",
              "kind": "text", "phase": "text", "bytes_total": "bigint",
              "bytes_read": "bigint", "bytes_written": "bigint",
              "progress_pct": "double", "active_seconds": "double",
              "eta_seconds": "double"})

    def cip_rows():
        for s in engine.compactions.active.snapshot():
            yield {"id": s["id"], "keyspace_name": s["keyspace"],
                   "table_name": s["table"], "kind": s["kind"],
                   "phase": s["phase"], "bytes_total": s["total_bytes"],
                   "bytes_read": s["bytes_read"],
                   "bytes_written": s["bytes_written"],
                   "progress_pct": s["progress_pct"],
                   "active_seconds": s["active_seconds"],
                   "eta_seconds": (-1.0 if s["eta_seconds"] is None
                                   else s["eta_seconds"])}
    vs.register(VirtualTable(t_cip, cip_rows))

    # --- quarantined_sstables (storage/failures.py quarantine records):
    # corrupt sstables blacklisted out of the live set, with the error
    # that condemned them and where their components went
    t_quar = make_table(
        "system_views", "quarantined_sstables", pk=["keyspace_name"],
        ck=["table_name", "generation"],
        cols={"keyspace_name": "text", "table_name": "text",
              "generation": "int", "reason": "text",
              "quarantined_at": "bigint", "size_bytes": "bigint",
              "path": "text"})

    def quarantine_rows():
        for cfs in engine.stores.values():
            for q in list(getattr(cfs, "quarantined", [])):
                yield {"keyspace_name": cfs.table.keyspace,
                       "table_name": cfs.table.name,
                       "generation": q["generation"],
                       "reason": q.get("reason", "")[:200],
                       "quarantined_at": int(q.get("at", 0) * 1000),
                       "size_bytes": q.get("bytes", 0),
                       "path": q.get("path", "")}
    vs.register(VirtualTable(t_quar, quarantine_rows))

    t_metrics = make_table("system_views", "metrics", pk=["name"],
                           cols={"name": "text", "value": "double"})

    def metric_rows():
        from ..service.metrics import GLOBAL
        for k, v in sorted(GLOBAL.snapshot().items()):
            yield {"name": k, "value": float(v)}
        # engine-scoped compaction gauges (process-global registration
        # would cross-report between in-process nodes)
        for k, v in sorted(engine.compactions.gauges().items()):
            yield {"name": k, "value": float(v)}
        for cfs in engine.stores.values():
            base = f"table.{cfs.table.keyspace}.{cfs.table.name}"
            for k, v in cfs.metrics.items():
                yield {"name": f"{base}.{k}", "value": float(v)}
            # derived amplification gauges (the adaptive-compaction
            # input signals; same-source counters, see
            # ColumnFamilyStore.amplification)
            for k, v in cfs.amplification().items():
                yield {"name": f"{base}.{k}", "value": float(v)}
    vs.register(VirtualTable(t_metrics, metric_rows))

    # --- metrics_history (service/history.py): the retained
    # multi-resolution time series — raw rows are single samples,
    # coarse rows the sealed min/max/last/sum-preserving merge
    # buckets; rate_per_s is the counter rate between consecutive raw
    # samples (0 on the first sample and on coarse rows)
    t_mh = make_table("system_views", "metrics_history", pk=["name"],
                      ck=["resolution", "at_ms"],
                      cols={"name": "text", "resolution": "text",
                            "at_ms": "bigint", "last": "double",
                            "min": "double", "max": "double",
                            "sum": "double", "n": "int",
                            "rate_per_s": "double"})

    def mh_rows():
        svc = getattr(engine, "metrics_history", None)
        if svc is None:
            return
        for name in svc.names():
            prev = None
            for b in svc.query(name, "raw"):
                rate = 0.0
                if prev is not None and b["t1"] > prev["t1"]:
                    rate = max(b["last"] - prev["last"], 0.0) \
                        / (b["t1"] - prev["t1"])
                # at_ms is WALL-clock (epoch ms, via the service's
                # sample-time offset) so rows join against telemetry
                # snapshots and diagnostic-event timestamps
                yield {"name": name, "resolution": "raw",
                       "at_ms": int(svc.to_wall(b["t1"]) * 1000),
                       "last": b["last"], "min": b["min"],
                       "max": b["max"], "sum": b["sum"], "n": b["n"],
                       "rate_per_s": round(rate, 6)}
                prev = b
            for b in svc.query(name, "coarse"):
                yield {"name": name, "resolution": "coarse",
                       "at_ms": int(svc.to_wall(b["t1"]) * 1000),
                       "last": b["last"], "min": b["min"],
                       "max": b["max"], "sum": b["sum"], "n": b["n"],
                       "rate_per_s": 0.0}
    vs.register(VirtualTable(t_mh, mh_rows))

    # --- controller_decisions (control/loop.py): the adaptive
    # compaction controller's bounded decision ledger — every applied
    # strategy/knob change and every hysteresis/cooldown/freeze skip,
    # newest LEDGER_CAPACITY kept. `nodetool autocompaction history`
    # serves the same rows.
    t_ctrl = make_table(
        "system_views", "controller_decisions", pk=["id"],
        cols={"id": "bigint", "at": "bigint", "keyspace_name": "text",
              "table_name": "text", "regime": "text", "action": "text",
              "old": "text", "new": "text", "applied": "boolean",
              "reason": "text"})

    def controller_rows():
        ctrl = getattr(engine, "controller", None)
        for e in (ctrl.decisions() if ctrl else []):
            yield {"id": e["seq"], "at": e["at_ms"],
                   "keyspace_name": e.get("keyspace", ""),
                   "table_name": e.get("table", ""),
                   "regime": e.get("regime") or "",
                   "action": e.get("action", ""),
                   "old": str(e.get("old", "")),
                   "new": str(e.get("new", "")),
                   "applied": bool(e.get("applied")),
                   "reason": e.get("reason", "")}
    vs.register(VirtualTable(t_ctrl, controller_rows))

    t_slow = make_table("system_views", "slow_queries", pk=["id"],
                        cols={"id": "int", "query": "text",
                              "keyspace_name": "text",
                              "duration_ms": "double",
                              "parse_ms": "double",
                              "execute_ms": "double",
                              "serialize_ms": "double",
                              "at": "bigint",
                              "trace_session": "text"})

    def slow_rows():
        mon = getattr(engine, "monitor", None)
        for e in (mon.entries() if mon else []):
            yield {"id": e["id"], "query": e["query"],
                   "keyspace_name": e["keyspace"],
                   "duration_ms": e["duration_ms"],
                   "parse_ms": e.get("parse_ms", 0.0),
                   "execute_ms": e.get("execute_ms", 0.0),
                   "serialize_ms": e.get("serialize_ms", 0.0),
                   "at": e["at"],
                   "trace_session": e.get("trace_session") or ""}
    vs.register(VirtualTable(t_slow, slow_rows))

    # --- diagnostic_events (diag/DiagnosticEventService vtable role):
    # the typed event bus's recent rings, publication-ordered. Empty
    # until the diagnostic_events_enabled knob flips on.
    t_diag = make_table("system_views", "diagnostic_events", pk=["seq"],
                        cols={"seq": "bigint", "at": "bigint",
                              "type": "text", "fields": "text"})

    def diag_rows():
        import json as _json
        from ..service import diagnostics
        for ev in diagnostics.GLOBAL.events():
            # truncate VALUES, never the serialized document — the
            # fields cell must stay parseable JSON however long a
            # reason/path field came in
            fields = {k: (v[:200] if isinstance(v, str) else v)
                      for k, v in ev.fields.items()}
            yield {"seq": ev.seq, "at": int(ev.at * 1000),
                   "type": ev.type,
                   "fields": _json.dumps(fields, default=repr,
                                         sort_keys=True)}
    vs.register(VirtualTable(t_diag, diag_rows))

    # --- slos (service/slo.py): per-objective p99 vs target, error
    # budget remaining, breach/exhaustion tallies. A pure snapshot —
    # SELECTing this table never publishes events or dumps bundles
    # (that's `nodetool slostats`, which runs a real check())
    t_slo = make_table(
        "system_views", "slos", pk=["objective"],
        cols={"objective": "text", "metric": "text",
              "p99_us": "double", "target_us": "double",
              "breaching": "boolean", "breaches": "bigint",
              "budget_s": "double", "budget_remaining_s": "double",
              "exhausted": "boolean", "exhaustions": "bigint"})

    def slo_rows():
        svc = getattr(engine, "slo", None)
        for v in (svc.snapshot() if svc else []):
            yield {"objective": v["objective"], "metric": v["metric"],
                   "p99_us": v["p99_us"], "target_us": v["target_us"],
                   "breaching": v["breaching"],
                   "breaches": v["breaches"],
                   "budget_s": v["budget_s"],
                   "budget_remaining_s": v["budget_remaining_s"],
                   "exhausted": v["exhausted"],
                   "exhaustions": v["exhaustions"]}
    vs.register(VirtualTable(t_slo, slo_rows))

    # --- pipelines (utils/pipeline_ledger.py): per-stage busy/stall/
    # idle accounting for every multi-stage pipeline — the
    # where-did-the-wall-go surface (TPIE-style per-stage profiling)
    t_pipe = make_table("system_views", "pipelines", pk=["pipeline"],
                        ck=["stage"],
                        cols={"pipeline": "text", "stage": "text",
                              "busy_seconds": "double",
                              "stall_seconds": "double",
                              "idle_seconds": "double",
                              "items": "bigint", "bytes": "bigint",
                              "queue_high_water": "int"})

    def pipe_rows():
        from ..utils import pipeline_ledger
        for pname, stages in sorted(pipeline_ledger.snapshot_all()
                                    .items()):
            for sname, s in stages.items():
                yield {"pipeline": pname, "stage": sname,
                       "busy_seconds": s["busy_s"],
                       "stall_seconds": s["stall_s"],
                       "idle_seconds": s["idle_s"],
                       "items": s["items"], "bytes": s["bytes"],
                       "queue_high_water": s["queue_hwm"]}
    vs.register(VirtualTable(t_pipe, pipe_rows))

    # --- system_traces (tracing/TraceKeys role): completed sessions
    # (explicit TRACING ON + trace_probability-sampled) and their merged
    # coordinator+replica event timelines
    t_tsess = make_table("system_traces", "sessions", pk=["session_id"],
                         cols={"session_id": "text", "request": "text",
                               "started_at": "bigint",
                               "duration_us": "bigint",
                               "events": "int"})

    def tsess_rows():
        store = getattr(engine, "trace_store", None)
        for st in (store.sessions() if store else []):
            yield {"session_id": st.session_id, "request": st.request,
                   "started_at": int(st.started_at * 1000),
                   "duration_us": st.duration_us,
                   "events": len(st.events)}
    vs.register(VirtualTable(t_tsess, tsess_rows))

    t_tev = make_table("system_traces", "events", pk=["session_id"],
                       ck=["event_id"],
                       cols={"session_id": "text", "event_id": "int",
                             "activity": "text", "source": "text",
                             "source_elapsed": "bigint"})

    def tev_rows():
        store = getattr(engine, "trace_store", None)
        for st in (store.sessions() if store else []):
            for i, (us, src, activity) in enumerate(list(st.events)):
                yield {"session_id": st.session_id, "event_id": i,
                       "activity": activity, "source": src,
                       "source_elapsed": int(us)}
    vs.register(VirtualTable(t_tev, tev_rows))

    # --- device_profile (the observability layer over ops/merge.py):
    # per-kernel compile/dispatch/execute split + recompiles-by-shape,
    # plus the aggregated compaction phase timings (compress/io_write/
    # seal/...) — one table, `kind` distinguishes the two row families
    t_dp = make_table("system_views", "device_profile", pk=["name"],
                      cols={"name": "text", "kind": "text",
                            "calls": "bigint", "compiles": "bigint",
                            "shapes": "bigint",
                            "compile_seconds": "double",
                            "dispatch_seconds": "double",
                            "execute_seconds": "double"})

    def dp_rows():
        from ..service.profiling import GLOBAL as kprof
        snap = kprof.snapshot()
        for name, k in sorted(snap["kernels"].items()):
            yield {"name": name, "kind": "kernel", "calls": k["calls"],
                   "compiles": k["compiles"], "shapes": k["shapes"],
                   "compile_seconds": k["compile_s"],
                   "dispatch_seconds": k["dispatch_s"],
                   "execute_seconds": k["execute_s"]}
        for phase, secs in sorted(snap["phases"].items()):
            yield {"name": f"phase.{phase}", "kind": "phase",
                   "calls": 0, "compiles": 0, "shapes": 0,
                   "compile_seconds": 0.0, "dispatch_seconds": 0.0,
                   "execute_seconds": secs}
    vs.register(VirtualTable(t_dp, dp_rows))

    # --- device_programs (observability layer 6, the registry view):
    # the full per-program accounting the generalized registry keeps —
    # compile vs warm dispatch vs execute, live tracked shapes +
    # evictions (the bounded-LRU churn signals), past-budget retraces
    # and the XLA cost analysis where the backend provides one
    t_dprog = make_table(
        "system_views", "device_programs", pk=["name"],
        cols={"name": "text", "calls": "bigint", "compiles": "bigint",
              "retraces": "bigint", "shape_count": "bigint",
              "shape_evictions": "bigint", "compile_seconds": "double",
              "dispatch_seconds": "double", "execute_seconds": "double",
              "cost_flops": "double", "cost_bytes": "double"})

    def dprog_rows():
        from ..service.profiling import GLOBAL as kprof
        for name, k in sorted(kprof.snapshot()["kernels"].items()):
            yield {"name": name, "calls": k["calls"],
                   "compiles": k["compiles"],
                   "retraces": k["retraces"],
                   "shape_count": k["shape_count"],
                   "shape_evictions": k["shape_evictions"],
                   "compile_seconds": k["compile_s"],
                   "dispatch_seconds": k["dispatch_s"],
                   "execute_seconds": k["execute_s"],
                   "cost_flops": k["cost_flops"],
                   "cost_bytes": k["cost_bytes"]}
    vs.register(VirtualTable(t_dprog, dprog_rows))

    # --- profiles (observability layer 6, the wall-clock half): the
    # sampler's folded stacks — the always-on ring plus every live and
    # retained finished session, hottest stacks first per target
    t_prof = make_table(
        "system_views", "profiles", pk=["target"],
        ck=["stack_id"],
        cols={"target": "text", "stack_id": "int", "state": "text",
              "thread": "text", "stack": "text", "samples": "bigint"})

    def prof_rows():
        from ..service.sampler import GLOBAL as sp
        st = sp.stats()
        targets = ["ring"] + st["sessions"] + st["finished_sessions"]
        for target in targets:
            try:
                lines = sp.collapsed(target)
            except ValueError:
                continue   # session sealed between stats() and here
            for i, line in enumerate(lines):
                body, _, count = line.rpartition(" ")
                state, tname, *frames = body.split(";")
                yield {"target": target, "stack_id": i,
                       "state": state, "thread": tname,
                       "stack": ";".join(frames),
                       "samples": int(count)}
    vs.register(VirtualTable(t_prof, prof_rows))

    # --- settings (db/virtual/SettingsTable.java): the typed config,
    # live values, with mutability flag
    t_settings = make_table("system_views", "settings", pk=["name"],
                           cols={"name": "text", "value": "text",
                                 "mutable": "boolean"})
    vs.register(VirtualTable(t_settings, lambda: (
        {"name": n, "value": v, "mutable": m}
        for n, v, m in engine.settings.all())))

    # --- caches (db/virtual/CachesTable.java): chunk + key + row
    def cache_rows():
        from . import chunk_cache, key_cache, row_cache
        s = chunk_cache.GLOBAL.stats()
        yield {"name": "chunks", "entries": s.get("entries", 0),
               "size_bytes": s.get("bytes", 0),
               "capacity_bytes": s.get("capacity", 0),
               "hits": s.get("hits", 0), "misses": s.get("misses", 0)}
        k = key_cache.GLOBAL.stats()
        yield {"name": "keys", "entries": k.get("entries", 0),
               "size_bytes": 0, "capacity_bytes": 0,
               "hits": k.get("hits", 0), "misses": k.get("misses", 0)}
        # per-table handle hit/miss counters (engine-scoped), shared
        # service bytes/capacity/entry totals (storage/row_cache.py)
        row_hits = row_miss = rows_cached = 0
        for cfs in engine.stores.values():
            rc = cfs.row_cache
            if rc is not None:
                row_hits += rc.hits
                row_miss += rc.misses
                rows_cached += len(rc)
        r = row_cache.GLOBAL.stats()
        yield {"name": "rows", "entries": rows_cached,
               "size_bytes": r.get("bytes", 0),
               "capacity_bytes": r.get("capacity", 0),
               "hits": row_hits, "misses": row_miss}

    t_caches = make_table("system_views", "caches", pk=["name"],
                          cols={"name": "text", "entries": "bigint",
                                "size_bytes": "bigint",
                                "capacity_bytes": "bigint",
                                "hits": "bigint", "misses": "bigint"})
    vs.register(VirtualTable(t_caches, cache_rows))

    # --- disk_usage (db/virtual/DisksTable role, per-table granularity)
    t_disk = make_table("system_views", "disk_usage", pk=["keyspace_name"],
                        ck=["table_name"],
                        cols={"keyspace_name": "text",
                              "table_name": "text", "mebibytes": "double",
                              "sstables": "int"})

    def disk_rows():
        for cfs in engine.stores.values():
            live = cfs.live_sstables()
            yield {"keyspace_name": cfs.table.keyspace,
                   "table_name": cfs.table.name,
                   "mebibytes": round(sum(s.size_bytes for s in live)
                                      / 2**20, 3),
                   "sstables": len(live)}
    vs.register(VirtualTable(t_disk, disk_rows))

    # --- memtables
    t_mem = make_table("system_views", "memtables", pk=["keyspace_name"],
                       ck=["table_name"],
                       cols={"keyspace_name": "text", "table_name": "text",
                             "cells": "bigint", "payload_bytes": "bigint"})

    def mem_rows():
        for cfs in engine.stores.values():
            m = cfs.memtable
            yield {"keyspace_name": cfs.table.keyspace,
                   "table_name": cfs.table.name, "cells": len(m),
                   "payload_bytes": getattr(m, "live_bytes", 0)}
    vs.register(VirtualTable(t_mem, mem_rows))

    # --- thread_pools (db/virtual/ThreadPoolsTable): the executors that
    # exist in this runtime — compaction worker + per-writer syncers
    t_tp = make_table("system_views", "thread_pools", pk=["name"],
                      cols={"name": "text", "active": "int",
                            "pending": "int", "completed": "bigint"})

    def tp_rows():
        from ..tools.nodetool import tpstats
        for p in tpstats(engine):   # single source for nodetool + vtable
            yield {"name": p["pool"], "active": p["active"],
                   "pending": p["pending"], "completed": p["completed"]}
    vs.register(VirtualTable(t_tp, tp_rows))

    # --- indexes (SAI/SASI registry)
    t_idx = make_table("system_views", "indexes", pk=["keyspace_name"],
                       ck=["table_name", "index_name"],
                       cols={"keyspace_name": "text", "table_name": "text",
                             "index_name": "text", "column_name": "text",
                             "kind": "text"})

    def index_rows():
        im = getattr(engine, "indexes", None)
        if im is None:
            return
        for (ks, tbl, name), key in sorted(im.by_name.items()):
            meta = im.meta.get(key, {})
            yield {"keyspace_name": ks, "table_name": tbl,
                   "index_name": name, "column_name": key[2],
                   "kind": meta.get("custom_class") or "SAI"}
    vs.register(VirtualTable(t_idx, index_rows))

    # --- triggers
    t_trig = make_table("system_views", "triggers", pk=["keyspace_name"],
                        ck=["table_name", "trigger_name"],
                        cols={"keyspace_name": "text",
                              "table_name": "text", "trigger_name": "text",
                              "source": "text"})

    def trigger_rows():
        tm = getattr(engine, "triggers", None)
        if tm is None:
            return
        for (ks, tbl), by_name in sorted(tm.triggers.items()):
            for name, source in sorted(by_name.items()):
                yield {"keyspace_name": ks, "table_name": tbl,
                       "trigger_name": name, "source": source[:200]}
    vs.register(VirtualTable(t_trig, trigger_rows))

    # --- snapshots (db/virtual/SnapshotsTable)
    t_snap = make_table("system_views", "snapshots", pk=["tag"],
                        ck=["keyspace_name", "table_name"],
                        cols={"tag": "text", "keyspace_name": "text",
                              "table_name": "text", "files": "int",
                              "created_at": "text"})

    def snap_rows():
        from .snapshot import list_snapshots
        for cfs in engine.stores.values():
            for s in list_snapshots(cfs):
                yield {"tag": s["tag"],
                       "keyspace_name": cfs.table.keyspace,
                       "table_name": cfs.table.name,
                       "files": len(s.get("files", [])),
                       "created_at": str(s.get("created_at", ""))}
    vs.register(VirtualTable(t_snap, snap_rows))

    # --- guardrail thresholds + recent warnings
    t_guard = make_table("system_views", "guardrails", pk=["name"],
                         cols={"name": "text", "value": "bigint"})

    def guard_rows():
        import dataclasses as _dc
        g = engine.guardrails
        for f in _dc.fields(g):
            if f.name == "warnings":
                continue
            yield {"name": f.name, "value": int(getattr(g, f.name))}
    vs.register(VirtualTable(t_guard, guard_rows))

    t_gwarn = make_table("system_views", "guardrail_warnings", pk=["id"],
                         cols={"id": "int", "message": "text"})
    vs.register(VirtualTable(t_gwarn, lambda: (
        {"id": i, "message": w}
        for i, w in enumerate(engine.guardrails.warnings))))

    # --- commitlog: a `<status>` summary row (segment count, oldest
    # dirty segment, writers parked on the group-commit barrier, sync
    # failures — CommitLogMetrics role) plus one row per segment file
    t_cl = make_table("system_views", "commitlog", pk=["name"],
                      cols={"name": "text", "size_bytes": "bigint",
                            "segments": "int", "oldest_dirty": "int",
                            "pending_syncs": "int",
                            "sync_failures": "bigint"})

    def cl_rows():
        cl = engine.commitlog
        if cl is None:
            return
        st = cl.stats()
        od = st["oldest_dirty"]
        yield {"name": "<status>", "size_bytes": st["total_bytes"],
               "segments": st["segments"],
               "oldest_dirty": -1 if od is None else od,
               "pending_syncs": st["pending_syncs"],
               "sync_failures": st["sync_failures"]}
        for fn, sz in st["files"]:
            yield {"name": fn, "size_bytes": sz, "segments": 0,
                   "oldest_dirty": -1, "pending_syncs": 0,
                   "sync_failures": 0}
    vs.register(VirtualTable(t_cl, cl_rows))

    # --- batches on disk (batchlog backlog)
    t_bl = make_table("system_views", "batch_metrics", pk=["name"],
                      cols={"name": "text", "value": "bigint"})

    def bl_rows():
        import os as _os
        bl = getattr(engine, "batchlog", None)
        n = 0
        if bl is not None and _os.path.isdir(bl.directory):
            n = len([f for f in _os.listdir(bl.directory)
                     if f.startswith("batch-")])
        yield {"name": "pending_batches", "value": n}
    vs.register(VirtualTable(t_bl, bl_rows))

    # --- system_properties (db/virtual/SystemPropertiesTable): the
    # environment the node actually runs with
    t_props = make_table("system_views", "system_properties", pk=["name"],
                         cols={"name": "text", "value": "text"})

    def prop_rows():
        import os as _os
        import sys as _sys
        yield {"name": "python_version", "value": _sys.version.split()[0]}
        yield {"name": "platform", "value": _sys.platform}
        yield {"name": "data_dir", "value": engine.data_dir}
        for k in sorted(_os.environ):
            if k.startswith(("JAX_", "XLA_", "CTPU_")):
                yield {"name": k, "value": _os.environ[k][:200]}
    vs.register(VirtualTable(t_props, prop_rows))

    # --- cql latency percentiles (db/virtual/QueriesTable +
    # ClientRequestMetrics): served from the global latency histogram
    t_cqlm = make_table("system_views", "cql_metrics", pk=["name"],
                        cols={"name": "text", "p50_us": "double",
                              "p95_us": "double",
                              "p99_us": "double", "max_us": "double",
                              "count": "bigint"})

    def cqlm_rows():
        from ..service.metrics import GLOBAL
        for name in ("cql.request", "request.read", "request.write",
                     "request.range"):
            s = GLOBAL.hist(name).summary()
            yield {"name": name, "p50_us": s["p50_us"],
                   "p95_us": s["p95_us"], "p99_us": s["p99_us"],
                   "max_us": s["max_us"], "count": s["count"]}
    vs.register(VirtualTable(t_cqlm, cqlm_rows))

    return vs


def build_node_virtuals(node) -> VirtualSchema:
    """Cluster-aware virtuals (system.peers etc.) for a Node backend."""
    vs = build_engine_virtuals(node.engine)

    t_peers = make_table("system", "peers", pk=["peer"],
                         cols={"peer": "text", "data_center": "text",
                               "rack": "text", "alive": "boolean",
                               "tokens": "int"})

    def peer_rows():
        for ep, toks in node.ring.endpoints.items():
            if ep == node.endpoint:
                continue
            yield {"peer": ep.name, "data_center": ep.dc, "rack": ep.rack,
                   "alive": node.is_alive(ep), "tokens": len(toks)}
    vs.register(VirtualTable(t_peers, peer_rows))

    # --- gossip_info (db/virtual/GossipInfoTable): per-endpoint state +
    # phi from the accrual detector
    t_gossip = make_table("system_views", "gossip_info", pk=["endpoint"],
                          cols={"endpoint": "text", "generation": "bigint",
                                "heartbeat": "bigint", "alive": "boolean",
                                "phi": "double"})

    def gossip_rows():
        g = node.gossiper
        now = g.clock()
        with g._lock:
            states = dict(g.states)
        for ep, st in states.items():
            phi = 0.0 if ep == g.ep else g.detector.phi(st, now)
            yield {"endpoint": ep.name, "generation": st.generation,
                   "heartbeat": st.version,
                   "alive": ep == g.ep or node.is_alive(ep),
                   "phi": round(float(phi), 3)}
    vs.register(VirtualTable(t_gossip, gossip_rows))

    # --- internode messaging counters (InternodeInbound/OutboundTable)
    t_msg = make_table("system_views", "internode_metrics", pk=["name"],
                       cols={"name": "text", "value": "bigint"})
    vs.register(VirtualTable(t_msg, lambda: (
        {"name": k, "value": int(v)}
        for k, v in sorted(node.messaging.metrics.items()))))

    # --- pending hints per target (PendingHintsTable)
    t_hints = make_table("system_views", "pending_hints", pk=["target"],
                         cols={"target": "text", "bytes_on_disk": "bigint",
                               "written": "bigint", "replayed": "bigint"})

    def hint_rows():
        from ..tools.nodetool import listpendinghints
        m = node.hints.metrics
        for h in listpendinghints(node):   # single source: nodetool+vtable
            yield {"target": h["target"], "bytes_on_disk": h["bytes"],
                   "written": m["written"], "replayed": m["replayed"]}
    vs.register(VirtualTable(t_hints, hint_rows))

    # --- streaming sessions (StreamingVirtualTable)
    t_stream = make_table("system_views", "streaming", pk=["id"],
                          cols={"id": "int", "peer": "text",
                                "direction": "text", "keyspace_name": "text",
                                "table_name": "text", "status": "text",
                                "files": "int", "bytes": "bigint"})

    def stream_rows():
        svc = getattr(node, "streams", None)
        for i, s in enumerate(_snapshot(svc.sessions) if svc else []):
            yield {"id": i, "peer": s["peer"], "direction": s["direction"],
                   "keyspace_name": s["keyspace"],
                   "table_name": s["table"], "status": s["status"],
                   "files": s["files"], "bytes": s["bytes"]}
    vs.register(VirtualTable(t_stream, stream_rows))

    # --- live sessioned transfers (cluster/stream_session.py): chunk
    # and byte progress while a session is IN FLIGHT — the `streaming`
    # table above holds only terminal summaries
    t_streams = make_table("system_views", "streams", pk=["id"],
                           cols={"id": "text", "peer": "text",
                                 "direction": "text",
                                 "keyspace_name": "text",
                                 "table_name": "text", "kind": "text",
                                 "status": "text",
                                 "chunks_total": "bigint",
                                 "chunks_done": "bigint",
                                 "bytes_total": "bigint",
                                 "bytes_done": "bigint"})

    def live_stream_rows():
        svc = getattr(node, "streams", None)
        if svc is None or not hasattr(svc, "progress"):
            return
        for s in svc.progress():
            yield {"id": s["sid"], "peer": s["peer"],
                   "direction": s["direction"],
                   "keyspace_name": s["keyspace"],
                   "table_name": s["table"], "kind": s["kind"],
                   "status": s["status"],
                   "chunks_total": s["chunks_total"],
                   "chunks_done": s["chunks_done"],
                   "bytes_total": s["bytes_total"],
                   "bytes_done": s["bytes_done"]}
    vs.register(VirtualTable(t_streams, live_stream_rows))

    # --- repair sessions
    t_rep = make_table("system_views", "repairs", pk=["id"],
                       cols={"id": "int", "keyspace_name": "text",
                             "table_name": "text", "incremental": "boolean",
                             "replicas": "int", "ranges_synced": "int"})

    def repair_rows():
        svc = getattr(node, "repair", None)
        for i, s in enumerate(_snapshot(svc.history) if svc else []):
            yield {"id": i, "keyspace_name": s["keyspace"],
                   "table_name": s["table"],
                   "incremental": s["incremental"],
                   "replicas": s["replicas"],
                   "ranges_synced": int(s.get("ranges_synced", 0))}
    vs.register(VirtualTable(t_rep, repair_rows))

    # --- connected native-protocol clients (ClientsTable)
    t_cli = make_table("system_views", "clients", pk=["id"],
                       cols={"id": "int", "address": "text",
                             "username": "text", "keyspace_name": "text",
                             "protocol_version": "int",
                             "requests": "bigint",
                             "in_flight": "int",
                             "rate_limited": "bigint"})

    def client_rows():
        from ..tools.nodetool import clientstats
        for c in clientstats(node):   # single source: nodetool + vtable
            yield {"id": c["id"], "address": c["address"],
                   "username": c["user"], "keyspace_name": c["keyspace"],
                   "protocol_version": c["version"],
                   "requests": c["requests"],
                   "in_flight": c["in_flight"],
                   "rate_limited": c["rate_limited"]}
    vs.register(VirtualTable(t_cli, client_rows))

    # --- token ownership (TokensTable / nodetool ring backing)
    t_tok = make_table("system_views", "tokens", pk=["endpoint"],
                       ck=["token"],
                       cols={"endpoint": "text", "token": "bigint"})

    def token_rows():
        for ep, toks in node.ring.endpoints.items():
            for t in sorted(toks):
                yield {"endpoint": ep.name, "token": int(t)}
    vs.register(VirtualTable(t_tok, token_rows))

    # --- coordinator latencies (CoordinatorReadLatency metrics): the
    # dynamic-snitch EWMA per peer
    t_lat = make_table("system_views", "coordinator_read_latency",
                       pk=["endpoint"],
                       cols={"endpoint": "text", "ewma_ms": "double"})

    def lat_rows():
        with node.proxy._lat_lock:
            snap = dict(node.proxy._latency)
        for ep, s in sorted(snap.items(), key=lambda kv: kv[0].name):
            yield {"endpoint": ep.name, "ewma_ms": round(s * 1000.0, 3)}
    vs.register(VirtualTable(t_lat, lat_rows))
    return vs
