"""Change data capture: a durable per-table stream of committed writes.

Reference counterpart: db/commitlog/CommitLogSegmentManagerCDC.java (the
reference hardlinks commitlog segments containing cdc-enabled tables'
writes into cdc_raw/ for consumers). The redesign here writes an
explicit per-table CDC log at apply time — the consumer reads clean,
single-table, CRC-framed mutation records instead of scanning shared
commitlog segments, and the space-cap semantics carry over
(cdc_total_space: writes to cdc tables FAIL when consumers lag, exactly
the reference's WriteTimeout-on-full behaviour).

Enable per table: CREATE TABLE ... WITH cdc = true. Consume:
    for offset, mutation in engine.cdc.read(table_id): ...
    engine.cdc.discard(table_id, upto_offset)   # consumer checkpoint
"""
from __future__ import annotations

import os
import struct
import threading
import zlib

from .mutation import Mutation

DEFAULT_SPACE_CAP = 64 << 20   # cdc_total_space default-ish bound


class CDCFullException(Exception):
    """cdc_raw is at capacity: the consumer is not keeping up (the
    reference fails cdc-table writes the same way)."""


class CDCLog:
    def __init__(self, directory: str,
                 space_cap: int = DEFAULT_SPACE_CAP):
        self.directory = directory
        self.space_cap = space_cap
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, table_id) -> str:
        return os.path.join(self.directory, f"{table_id.hex}.cdc")

    def append(self, mutation: Mutation) -> None:
        payload = mutation.serialize()
        frame = struct.pack("<II", len(payload), zlib.crc32(payload)) \
            + payload
        path = self._path(mutation.table_id)
        with self._lock:
            size = os.path.getsize(path) if os.path.exists(path) else 0
            if size + len(frame) > self.space_cap:
                raise CDCFullException(
                    f"cdc_raw at capacity for table {mutation.table_id}")
            with open(path, "ab") as f:
                f.write(frame)

    def read(self, table_id, from_offset: int = 0):
        """Yield (next_offset, Mutation) from the table's stream; a torn
        tail ends the iteration cleanly."""
        path = self._path(table_id)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            f.seek(from_offset)
            data = f.read()
        pos = 0
        while pos + 8 <= len(data):
            ln, crc = struct.unpack_from("<II", data, pos)
            body = data[pos + 8:pos + 8 + ln]
            if len(body) < ln or zlib.crc32(body) != crc:
                return
            pos += 8 + ln
            yield from_offset + pos, Mutation.deserialize(body)

    def discard(self, table_id, upto_offset: int) -> None:
        """Consumer checkpoint: drop everything before upto_offset (the
        reference's cdc_raw file deletion after consumption)."""
        path = self._path(table_id)
        with self._lock:
            if not os.path.exists(path):
                return
            with open(path, "rb") as f:
                f.seek(upto_offset)
                rest = f.read()
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(rest)
            os.replace(tmp, path)

    def size(self, table_id) -> int:
        path = self._path(table_id)
        return os.path.getsize(path) if os.path.exists(path) else 0
