"""Device-resident compaction rounds (ops/device_write.py): byte
identity with the serial host path, the fused META serialize kernel
pinned against the host builder, adversarial completion-order /
knob-flip / EIO-unwind behavior of the device→host handshake, and the
hot-reloadable `compaction_decode_ahead` knob."""
from __future__ import annotations

import hashlib
import os
import time

import numpy as np
import pytest

from cassandra_tpu.compaction.task import CompactionTask
from cassandra_tpu.ops import device_write as dwrite
from cassandra_tpu.schema import TableParams, make_table
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.cellbatch import CellBatchBuilder
from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
from cassandra_tpu.storage.sstable.writer import build_meta_block
from cassandra_tpu.storage.table import ColumnFamilyStore
from cassandra_tpu.tools import bulk
from cassandra_tpu.utils import faultfs
from cassandra_tpu.ops.codec import CompressionParams

N_CELLS = 60_000


def _table(name: str):
    return make_table(
        "devres", name, pk=["id"], ck=["c"],
        cols={"id": "int", "c": "int", "v": "blob"},
        params=TableParams(compression=CompressionParams(
            "LZ4Compressor", chunk_length=16 * 1024)))


def _build_inputs(cfs, table, n_ssts=3, n=N_CELLS, deletions=True):
    now = 1_700_000_000   # fixed: legs built at different wall times
    #                       must produce identical fixtures
    rng = np.random.default_rng(7)
    vcol = table.columns["v"].column_id
    for gen in range(1, n_ssts + 1):
        b = CellBatchBuilder(table)
        for p in range(200):
            pk = table.serialize_partition_key([p])
            if deletions and gen == 2 and p % 9 == 0:
                b.add_partition_deletion(pk, 5_000_000, ldt=now - 100)
            for c in range(n // 200 // n_ssts):
                ck = table.serialize_clustering([c])
                ts = 1_000_000 * gen + c
                if deletions and (p + c) % 13 == 0:
                    b.add_tombstone(pk, ck, vcol, ts, ldt=now - 50)
                elif deletions and (p + c) % 17 == 0:
                    # equal-ts duplicates across inputs: the device
                    # flags them ambiguous -> per-round host fallback
                    b.add_cell(pk, ck, vcol,
                               rng.integers(0, 256, 24,
                                            dtype=np.uint8).tobytes(),
                               999_999)
                else:
                    b.add_cell(pk, ck, vcol,
                               rng.integers(0, 256, 24,
                                            dtype=np.uint8).tobytes(),
                               ts)
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=200)
        w.append(cb.merge_sorted([b.seal()], now=0))
        w.finish()


def _build_big(cfs, table, n_ssts=3, n_per=140_000, seed=5):
    """Multi-segment inputs (vectorized build): each sstable spans 3
    Data.db segments, so rolls and decode-ahead fetches really happen."""
    rng = np.random.default_rng(seed)
    for gen in range(1, n_ssts + 1):
        pk = rng.integers(0, 500, n_per)
        ck = rng.integers(0, 100_000, n_per)
        vals = rng.integers(0, 256, (n_per, 24), dtype=np.uint8)
        ts = rng.integers(1, 1 << 40, n_per).astype(np.int64)
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=500)
        w.append(cb.merge_sorted([bulk.build_int_batch(table, pk, ck,
                                                       vals, ts)]))
        w.finish()


def _hashes(directory: str) -> dict:
    comps = ("Data.db", "Index.db", "Partitions.db", "Filter.db",
             "Statistics.db", "Digest.crc32")
    out = {}
    for fn in sorted(os.listdir(directory)):
        p = os.path.join(directory, fn)
        if os.path.isfile(p) and any(fn.endswith(c) for c in comps):
            with open(p, "rb") as f:
                out[fn] = hashlib.sha256(f.read()).hexdigest()
    return out


def _compact(tmp_path, tag: str, table, **task_kw) -> dict:
    d = str(tmp_path / tag)
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_inputs(cfs, table)
    cfs.reload_sstables()
    task = CompactionTask(cfs, cfs.tracker.view(), **task_kw)
    task.execute()
    h = _hashes(cfs.directory)
    for r in cfs.live_sstables():
        r.close()
    return h


# ------------------------------------------------------- serialize kernel --

def test_meta_kernel_matches_host_builder():
    """The fused device META kernel and the host build_meta_block must
    emit identical bytes — including wraparound ts deltas at extreme
    timestamps — and identical stats reductions."""
    rng = np.random.default_rng(3)
    n = 4096
    ts = rng.integers(-(1 << 62), 1 << 62, n).astype(np.int64)
    ts[:4] = [np.iinfo(np.int64).min, np.iinfo(np.int64).max, -1, 0]
    ldt = rng.integers(-(1 << 31), 1 << 31, n).astype(np.int32)
    ttl = rng.integers(0, 1 << 20, n).astype(np.int32)
    flags = rng.integers(0, 256, n).astype(np.uint8)
    fl = rng.integers(0, 1 << 16, n).astype(np.uint32)
    vr = rng.integers(0, 1 << 12, n).astype(np.uint32)
    host = build_meta_block(ts, ldt, ttl, flags,
                            fl.astype("<u4"), vr.astype("<u4"))
    import jax.numpy as jnp
    with np.errstate(over="ignore"):
        uts = ts.astype(np.uint64) ^ np.uint64(1 << 63)
    meta_d, st = dwrite._meta_block_kernel(
        jnp.asarray((uts >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((uts & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        jnp.asarray(ldt), jnp.asarray(ttl), jnp.asarray(flags),
        jnp.asarray(fl), jnp.asarray(vr))
    assert bytes(np.asarray(meta_d)) == bytes(host)
    assert dwrite._uts_pair_to_i64(st[0], st[1]) == int(ts.min())
    assert dwrite._uts_pair_to_i64(st[2], st[3]) == int(ts.max())
    assert int(st[4]) == int(ldt.min())
    assert int(st[5]) == int(ldt.max())
    from cassandra_tpu.storage.cellbatch import DEATH_FLAGS
    assert int(st[6]) == int(((flags & DEATH_FLAGS) != 0).sum())


# ----------------------------------------------------------- byte identity --

def test_device_resident_identical_to_serial(tmp_path):
    table = _table("ident")
    serial = _compact(tmp_path, "serial", table, pipelined_io=False,
                      compress_pool=0, decode_ahead=False)
    device = _compact(tmp_path, "device", table, engine="device",
                      use_device=True, pipelined_io=True,
                      compress_pool=0, decode_ahead=False)
    assert serial and device == serial


def test_device_resident_roll_identical(tmp_path):
    """Output rolling (max_output_bytes) flushes the device lane's
    pending partial into the finishing writer — the exact cells the
    host path's finish() would cut. Both legs run the synchronous
    write path (pipelined_io=False) so the published offset the roll
    check reads is timing-independent and the roll points — and
    therefore every component byte — must match exactly."""
    table = _table("roll")

    def leg(tag, **kw):
        d = str(tmp_path / tag)
        cfs = ColumnFamilyStore(table, d, commitlog=None)
        _build_big(cfs, table)
        cfs.reload_sstables()
        CompactionTask(cfs, cfs.tracker.view(), pipelined_io=False,
                       compress_pool=0, decode_ahead=False,
                       round_cells=150_000, max_output_bytes=1,
                       **kw).execute()
        h = _hashes(cfs.directory)
        for r in cfs.live_sstables():
            r.close()
        return h

    serial = leg("serial")
    device = leg("device", engine="device", use_device=True)
    assert len(serial) > 6   # really rolled (> 1 output sstable)
    assert device == serial


def test_reverse_completion_order_drains_in_order(tmp_path):
    """Round 0's collect is delayed until rounds 1-2's device programs
    completed — the in-flight rounds finish in REVERSE order, and the
    submit-order drain must still produce identical bytes."""
    table = _table("revorder")
    serial = _compact(tmp_path, "serial", table, pipelined_io=False,
                      compress_pool=0, decode_ahead=False,
                      round_cells=30_000)
    dwrite._collect_seq = 0
    dwrite._TEST_COLLECT_DELAY = {0: 0.3, 1: 0.1}
    try:
        device = _compact(tmp_path, "device", table, engine="device",
                          use_device=True, pipelined_io=True,
                          compress_pool=0, decode_ahead=False,
                          round_cells=30_000)
    finally:
        dwrite._TEST_COLLECT_DELAY = None
    assert device == serial


# ------------------------------------------------------- decode-ahead knob --

def test_decode_ahead_knob_flip_mid_compaction(tmp_path):
    """The task re-reads the engine-scoped knob every round: flipping
    it off mid-compaction retires the prefetch thread at the next
    round boundary, and the output bytes are identical regardless of
    when (or how often) it flips."""
    table = _table("knobflip")
    # multi-segment inputs: merge rounds advance one segment span at a
    # time, so the task makes >= 4 rounds (= 4 knob reads)
    dp = str(tmp_path / "pinned")
    pcfs = ColumnFamilyStore(table, dp, commitlog=None)
    _build_big(pcfs, table, n_per=220_000, seed=11)
    pcfs.reload_sstables()
    CompactionTask(pcfs, pcfs.tracker.view(), pipelined_io=True,
                   compress_pool=0, decode_ahead=False,
                   round_cells=10_000).execute()
    pinned = _hashes(pcfs.directory)
    for r in pcfs.live_sstables():
        r.close()

    d = str(tmp_path / "flip")
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_big(cfs, table, n_per=220_000, seed=11)
    cfs.reload_sstables()
    calls = [0]

    def knob():
        calls[0] += 1
        return calls[0] <= 2    # on for two rounds, then OFF

    cfs.decode_ahead_fn = knob
    task = CompactionTask(cfs, cfs.tracker.view(), pipelined_io=True,
                          compress_pool=0, round_cells=10_000)
    assert task._decode_ahead_enabled() in (True, False)
    task.execute()
    # the knob was re-read every round (hot-reload contract) and bytes
    # match the pinned-off leg
    assert calls[0] >= 4
    assert _hashes(cfs.directory) == pinned
    for r in cfs.live_sstables():
        r.close()


def test_decode_ahead_eio_unwinds_with_inputs_live(tmp_path):
    """An EIO surfacing from a decode-ahead prefetched segment read
    must fail the task through the normal unwind: lifecycle txn rolled
    back, tmp components gone, input sstables still live and readable."""
    table = _table("eio")
    d = str(tmp_path / "store")
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_big(cfs, table)
    cfs.reload_sstables()
    inputs_before = list(cfs.tracker.view())
    # fire on the SECOND read of input 1's data — a later segment,
    # fetched by the decode-ahead helper (or, under unlucky
    # scheduling, a merge-thread extend): either path must unwind
    # identically
    faultfs.GLOBAL.arm("sstable.read", mode="error", after=1,
                       path_substr="-1-Data.db")
    try:
        task = CompactionTask(cfs, inputs_before, pipelined_io=True,
                              compress_pool=0, decode_ahead=True,
                              round_cells=100_000)
        with pytest.raises(OSError):
            task.execute()
    finally:
        faultfs.GLOBAL.disarm()
    # rollback left the inputs live and the directory clean
    assert list(cfs.tracker.view()) == inputs_before
    assert not [f for f in os.listdir(cfs.directory)
                if f.startswith("tmp-")]
    # the store still serves every partition from the untouched inputs
    from cassandra_tpu.storage.chunk_cache import GLOBAL as chunk_cache
    chunk_cache.clear()
    pk = table.serialize_partition_key([5])
    assert len(cfs.read_partition(pk, now=int(time.time()))) > 0
    for r in cfs.live_sstables():
        r.close()
