"""Sessioned streaming under fire: resume byte-identity after sender
and receiver death, atomic (TOC-last) landings, throttle + dispatch
liveness, repair sync over a flaky wire, and the legacy-path cap."""
import hashlib
import json
import os
import threading
import time

import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.cluster.stream_session import (MIN_TOKEN, StreamManager,
                                                  batch_from_bytes,
                                                  batch_to_bytes)
from cassandra_tpu.cluster.streaming import StreamPayloadTooLarge
from cassandra_tpu.utils import faultfs

MAX_TOKEN = (1 << 63) - 1


# ------------------------------------------------------------- helpers --

def _mk_cluster(tmp_path, n=3, rf=3, rows=250):
    c = LocalCluster(n, str(tmp_path), rf=rf)
    for nd in c.nodes:
        nd.proxy.timeout = 2.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              f"{{'class': 'SimpleStrategy', 'replication_factor': {rf}}}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    c.node(1).default_cl = ConsistencyLevel.ALL
    for i in range(rows):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, '{'x' * 60}{i}')")
    c.node(1).engine.store("ks", "kv").flush()
    return c


def _receiver_dirs(node):
    base = os.path.join(node.engine.data_dir, "streaming")
    out = []
    if os.path.isdir(base):
        for sid in sorted(os.listdir(base)):
            mpath = os.path.join(base, sid, "meta.json")
            if os.path.exists(mpath):
                with open(mpath) as f:
                    if json.load(f).get("role") == "receiver":
                        out.append(os.path.join(base, sid))
    return out


def _acked_count(node):
    n = 0
    for d in _receiver_dirs(node):
        p = os.path.join(d, "acked.log")
        if os.path.exists(p):
            with open(p) as f:
                n += sum(1 for _ in f)
    return n


def _gen_hashes(cfs, gens):
    """{component name: sha256 of contents} for the given generations.
    Contents never embed the generation, so two landings of the same
    source sstable hash identically regardless of local gen."""
    gens = set(gens)
    out = {}
    for fn in sorted(os.listdir(cfs.directory)):
        parts = fn.split("-", 2)
        if len(parts) == 3 and parts[1].isdigit() \
                and int(parts[1]) in gens:
            with open(os.path.join(cfs.directory, fn), "rb") as f:
                out[parts[2]] = hashlib.sha256(f.read()).hexdigest()
    return out


def _small_chunks(monkeypatch, chunk=512, window=4):
    monkeypatch.setattr(StreamManager, "CHUNK_SIZE", chunk)
    monkeypatch.setattr(StreamManager, "WINDOW", window)


def _stream_in_thread(node, owner, timeout):
    holder = {}

    def run():
        try:
            holder["res"] = node.streams.stream_range(
                owner, "ks", "kv", MIN_TOKEN, MAX_TOKEN, timeout=timeout)
        except Exception as e:
            holder["err"] = e

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th, holder


# ------------------------------------------------- resume byte identity --

def test_resume_after_sender_kill_byte_identity(tmp_path, monkeypatch):
    """Kill the SENDER mid-session: the receiver's journaled watermark
    survives, resume re-requests only the tail, and the landed
    components are sha256-identical to an unkilled transfer."""
    _small_chunks(monkeypatch)
    c = _mk_cluster(tmp_path)
    try:
        n1, n2, n3 = c.node(1), c.node(2), c.node(3)
        control = n2.streams.stream_range(
            n1.endpoint, "ks", "kv", MIN_TOKEN, MAX_TOKEN, timeout=30.0)
        assert control["files"] > 0
        want = _gen_hashes(n2.engine.store("ks", "kv"), control["gens"])
        assert want and "TOC.txt" in want

        faultfs.arm("stream.net", "latency", delay_s=0.03)
        try:
            th, holder = _stream_in_thread(n3, n1.endpoint, timeout=2.5)
            deadline = time.monotonic() + 10
            while _acked_count(n3) < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert _acked_count(n3) >= 3, "no chunks landed before kill"
            c.stop_node(1)   # sender dies mid-session
        finally:
            faultfs.disarm()
        th.join(timeout=15)
        assert "err" in holder, "session should fail with the sender dead"
        # durable state survived the failure: manifest + watermark
        assert _receiver_dirs(n3), "receiver session state must persist"
        watermark = _acked_count(n3)
        assert watermark >= 3

        c.restart_node(1)
        res = n3.streams.resume_incomplete(timeout=30.0)
        assert len(res) == 1 and "error" not in res[0], res
        got = _gen_hashes(n3.engine.store("ks", "kv"), res[0]["gens"])
        assert got == want
        assert _receiver_dirs(n3) == []   # completion sweeps the state
    finally:
        c.shutdown()


def test_resume_after_receiver_kill_byte_identity(tmp_path, monkeypatch):
    """Kill the RECEIVER mid-session, restart it, resume: only the
    missing tail is re-requested and the result is byte-identical."""
    _small_chunks(monkeypatch)
    c = _mk_cluster(tmp_path)
    try:
        n1, n2, n3 = c.node(1), c.node(2), c.node(3)
        control = n2.streams.stream_range(
            n1.endpoint, "ks", "kv", MIN_TOKEN, MAX_TOKEN, timeout=30.0)
        want = _gen_hashes(n2.engine.store("ks", "kv"), control["gens"])

        faultfs.arm("stream.net", "latency", delay_s=0.03)
        try:
            th, holder = _stream_in_thread(n3, n1.endpoint, timeout=20.0)
            deadline = time.monotonic() + 10
            while _acked_count(n3) < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert _acked_count(n3) >= 3
            c.stop_node(3)   # receiver dies mid-session
        finally:
            faultfs.disarm()
        th.join(timeout=15)
        assert "err" in holder
        watermark = _acked_count(n3)
        assert watermark >= 3

        c.restart_node(3)
        res = n3.streams.resume_incomplete(timeout=30.0)
        assert len(res) == 1 and "error" not in res[0], res
        got = _gen_hashes(n3.engine.store("ks", "kv"), res[0]["gens"])
        assert got == want
        assert _receiver_dirs(n3) == []
    finally:
        c.shutdown()


# --------------------------------------------------- atomic commit point --

def test_crash_before_toc_leaves_no_visible_sstable(tmp_path):
    """A landing killed at the TOC write leaves ZERO visible sstables
    (discover requires the TOC) and replay_directory sweeps the
    orphaned components at restart."""
    from cassandra_tpu.storage.lifecycle import replay_directory
    c = _mk_cluster(tmp_path, n=2, rf=2, rows=60)
    try:
        n1, n2 = c.node(1), c.node(2)
        cfs = n2.engine.store("ks", "kv")
        cfs.flush()
        before = {s.desc.generation for s in cfs.live_sstables()}
        with faultfs.inject("stream.land", "error",
                            path_substr="TOC.txt"):
            with pytest.raises(Exception):
                n2.streams.stream_range(n1.endpoint, "ks", "kv",
                                        MIN_TOKEN, MAX_TOKEN,
                                        timeout=10.0)
        cfs.reload_sstables()
        assert {s.desc.generation
                for s in cfs.live_sstables()} == before
        # the orphaned TOC-less components ARE on disk
        orphans = [fn for fn in os.listdir(cfs.directory)
                   if (p := fn.split("-", 2))
                   and len(p) == 3 and p[1].isdigit()
                   and int(p[1]) not in before]
        assert orphans, "crashed landing should leave orphan components"
        replay_directory(cfs.directory)   # the restart sweep
        left = [fn for fn in os.listdir(cfs.directory)
                if (p := fn.split("-", 2))
                and len(p) == 3 and p[1].isdigit()
                and int(p[1]) not in before]
        assert left == []
        cfs.reload_sstables()
        assert {s.desc.generation
                for s in cfs.live_sstables()} == before
    finally:
        c.shutdown()


# --------------------------------------- concurrency + dispatch liveness --

def test_concurrent_stream_and_quorum_writes_lose_nothing(tmp_path,
                                                          monkeypatch):
    """Bootstrap a 4th node while QUORUM writes hammer the same table:
    every write acknowledged during the join must be readable at QUORUM
    after it."""
    _small_chunks(monkeypatch, chunk=1024)
    c = _mk_cluster(tmp_path, rows=300)
    try:
        n2 = c.node(2)
        n2.default_cl = ConsistencyLevel.QUORUM
        s2 = c.session(2)
        s2.keyspace = "ks"
        written, errors = [], []
        stop = threading.Event()

        def writer():
            i = 10_000
            while not stop.is_set():
                try:
                    s2.execute(
                        f"INSERT INTO kv (k, v) VALUES ({i}, 'w{i}')")
                    written.append(i)
                    i += 1
                except Exception as e:   # a lost ack IS the failure
                    errors.append(e)
                    return

        faultfs.arm("stream.net", "latency", delay_s=0.005)
        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            time.sleep(0.1)   # writes in flight before the join starts
            c.add_node()
        finally:
            stop.set()
            t.join(timeout=10)
            faultfs.disarm()
        assert not errors, errors
        assert written, "writer made no progress during the join"
        s1 = c.session(1)
        s1.keyspace = "ks"
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        found = 0
        for i in range(0, len(written), 50):   # stay under the IN guardrail
            ks = ", ".join(str(k) for k in written[i:i + 50])
            found += len(
                s1.execute(f"SELECT k FROM kv WHERE k IN ({ks})").rows)
        assert found == len(written)
    finally:
        c.shutdown()


def test_gossip_and_reads_live_during_throttled_transfer(tmp_path,
                                                         monkeypatch):
    """A throttled bulk transfer must not stall the shared dispatch
    worker: reads and liveness probes stay responsive mid-stream, and
    the throughput knob hot-reloads to let the transfer finish."""
    _small_chunks(monkeypatch, chunk=2048)
    c = _mk_cluster(tmp_path, n=2, rf=2, rows=300)
    try:
        n1, n2 = c.node(1), c.node(2)
        # ~2 KiB/s: the transfer crawls until the knob is raised
        n1.engine.settings.set("stream_throughput_outbound", 0.002)
        th, holder = _stream_in_thread(n2, n1.endpoint, timeout=60.0)
        deadline = time.monotonic() + 10
        while not n2.streams.progress() and time.monotonic() < deadline:
            time.sleep(0.01)
        live = n2.streams.progress()
        assert live and live[0]["status"] in ("init", "requesting",
                                              "streaming")
        # the vtable surfaces the same live rows
        s2 = c.session(2)
        rows = s2.execute(
            "SELECT id, status FROM system_views.streams").dicts()
        assert rows and rows[0]["id"] == live[0]["sid"]
        s1 = c.session(1)
        s1.keyspace = "ks"
        for _ in range(3):   # dispatch stays live DURING the transfer
            t0 = time.monotonic()
            assert s1.execute("SELECT v FROM kv WHERE k = 1").rows
            assert time.monotonic() - t0 < 1.0
        assert n1.is_alive(n2.endpoint) and n2.is_alive(n1.endpoint)
        # hot-reload: the knob listener feeds the live token bucket
        n1.engine.settings.set("stream_throughput_outbound", 500.0)
        th.join(timeout=30)
        assert "res" in holder, holder.get("err")
        assert holder["res"]["files"] > 0
    finally:
        c.shutdown()


# ----------------------------------------------------- repair + legacy --

def test_repair_sync_converges_over_disconnect(tmp_path, monkeypatch):
    """A faultfs stream.net disconnect drops sync chunks on the floor;
    retransmit recovers and repair still converges."""
    import glob
    monkeypatch.setattr(StreamManager, "RETRANSMIT_BASE", 0.05)
    c = LocalCluster(3, str(tmp_path), rf=3)
    try:
        for nd in c.nodes:
            nd.proxy.timeout = 5.0
        n1 = c.node(1)
        n1.default_cl = ConsistencyLevel.ONE
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        victim = c.nodes[2]
        from cassandra_tpu.cluster.messaging import Verb
        c.filters.drop(verb=Verb.MUTATION_REQ, to=victim.endpoint)
        for i in range(100, 112):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'r{i}')")
        c.filters.clear()
        for nd in c.nodes:
            for f in glob.glob(os.path.join(nd.hints.directory, "*")):
                os.remove(f)
        t = c.schema.get_table("ks", "kv")
        missing = [i for i in range(100, 112)
                   if len(victim.engine.store("ks", "kv").read_partition(
                       t.columns["k"].cql_type.serialize(i))) == 0]
        assert missing, "victim should have missed writes"
        faultfs.arm("stream.net", "disconnect", times=2)
        try:
            stats = n1.repair.repair_table("ks", "kv")
        finally:
            fired = faultfs.GLOBAL.fires("stream.net")
            faultfs.disarm()
        assert stats["ranges_synced"] > 0
        assert fired > 0, "repair sync never crossed the armed fault"
        deadline = time.time() + 5
        store = victim.engine.store("ks", "kv")
        while time.time() < deadline and any(
                len(store.read_partition(
                    t.columns["k"].cql_type.serialize(i))) == 0
                for i in missing):
            time.sleep(0.1)
        assert all(len(store.read_partition(
            t.columns["k"].cql_type.serialize(i))) > 0 for i in missing)
    finally:
        c.shutdown()


def test_legacy_single_message_path_is_capped(tmp_path, monkeypatch):
    """An oversized legacy STREAM_REQ fails typed instead of
    materializing an unbounded payload on the dispatch worker."""
    from cassandra_tpu.cluster.streaming import StreamService
    c = _mk_cluster(tmp_path, n=2, rf=2, rows=80)
    try:
        n1, n2 = c.node(1), c.node(2)
        monkeypatch.setattr(StreamService, "LEGACY_MAX_BYTES", 64)
        with pytest.raises(StreamPayloadTooLarge):
            n2.streams.fetch_range(n1.endpoint, "ks", "kv",
                                   MIN_TOKEN, MAX_TOKEN, 5.0)
        # in-range data under the cap still flows (the compat contract)
        monkeypatch.setattr(StreamService, "LEGACY_MAX_BYTES",
                            64 * 1024 * 1024)
        files, leftover = n2.streams.fetch_range(
            n1.endpoint, "ks", "kv", MIN_TOKEN, MAX_TOKEN, 5.0)
        assert files
    finally:
        c.shutdown()


def test_batch_bytes_roundtrip(tmp_path):
    """The chunked wire codec round-trips a CellBatch exactly."""
    c = _mk_cluster(tmp_path, n=2, rf=2, rows=40)
    try:
        batch = c.node(1).engine.store("ks", "kv").scan_all()
        assert len(batch) > 0
        back = batch_from_bytes(batch_to_bytes(batch))
        assert len(back) == len(batch)
        assert back.sorted == batch.sorted
        assert back.pk_map == batch.pk_map
        import numpy as np
        for fld in ("lanes", "ts", "ldt", "ttl", "flags", "off",
                    "val_start", "payload"):
            assert np.array_equal(getattr(back, fld), getattr(batch, fld))
    finally:
        c.shutdown()


def test_netstats_and_metrics_surface(tmp_path):
    """nodetool netstats exposes live + terminal sessions; streaming.*
    counters move."""
    from cassandra_tpu.service.metrics import GLOBAL as METRICS
    from cassandra_tpu.tools import nodetool
    c = _mk_cluster(tmp_path, n=2, rf=2, rows=40)
    try:
        n1, n2 = c.node(1), c.node(2)
        before = METRICS.snapshot().get("streaming.sessions_completed", 0)
        res = n2.streams.stream_range(n1.endpoint, "ks", "kv",
                                      MIN_TOKEN, MAX_TOKEN, timeout=30.0)
        assert res["files"] > 0
        st = nodetool.netstats(n2)
        assert "streams" in st and isinstance(st["streams"], list)
        assert any(s["status"] == "complete" for s in st["streaming"])
        snap = METRICS.snapshot()
        assert snap.get("streaming.sessions_completed", 0) > before
        assert snap.get("streaming.chunks_sent", 0) > 0
    finally:
        c.shutdown()
