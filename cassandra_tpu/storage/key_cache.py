"""Key cache: partition-key -> partition location, shared by readers.

Reference counterpart: cache/KeyCacheKey.java + the key cache in
CacheService.java:108 — avoids the partition-index walk on repeat point
reads. Matters most for summary-mode sstables (large partition
directories kept downsampled in memory, storage/sstable/reader.py):
a hit skips the on-disk directory bracket scan entirely.

Entries key on (directory, generation, pk) — generation-scoped like the
chunk cache, so stale entries can never serve a new sstable. Persisted
across restarts by storage/saved_caches.py (AutoSavingCache role).
"""
from __future__ import annotations

import threading
from ..utils import lockwitness
from collections import OrderedDict


class KeyCache:
    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._lock = lockwitness.make_lock("storage.key_cache")
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple):
        with self._lock:
            v = self._lru.get(key)
            if v is None:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key: tuple, value: tuple) -> None:
        with self._lock:
            self._lru[key] = value
            self._lru.move_to_end(key)
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)

    # rough per-entry footprint: the (directory, generation, pk) key
    # strings/ints plus the location tuple. The byte-denominated
    # `key_cache_size` knob maps onto entry capacity through this.
    APPROX_ENTRY_BYTES = 512

    def set_capacity_bytes(self, nbytes) -> None:
        """Hot-resize from the `key_cache_size` knob (bytes); shrinking
        evicts LRU-first immediately. 0 DISABLES the cache (the repo's
        cache-size knob convention: puts evict instantly, every get
        misses); positive sizes floor at 1024 entries."""
        with self._lock:
            self.capacity = 0 if int(nbytes) <= 0 else max(
                1024, int(nbytes) // self.APPROX_ENTRY_BYTES)
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()

    def invalidate_generation(self, directory: str, generation: int):
        """Drop a dead sstable's entries eagerly (truncate path — the
        generation number can be REUSED by a store recreated over the
        same directory)."""
        with self._lock:
            dead = [k for k in self._lru
                    if k[0] == directory and k[1] == generation]
            for k in dead:
                del self._lru[k]

    def keys(self) -> list[tuple]:
        with self._lock:
            return list(self._lru)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._lru), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses}


GLOBAL = KeyCache()
