"""Storage engine end-to-end: write path (commitlog + memtable), flush,
read path (memtable + sstables merge), crash recovery by replay.
(Reference test model: CQLTester-based storage tests + CommitLogTest.)"""
import os
import random

import numpy as np
import pytest

from cassandra_tpu.schema import Schema, make_table, COL_REGULAR_BASE
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.mutation import Mutation
from cassandra_tpu.storage.rows import row_to_dict, rows_from_batch
from cassandra_tpu.utils import timeutil


def new_engine(tmp_path, **kw):
    schema = Schema()
    schema.create_keyspace("ks")
    t = make_table("ks", "users", pk=["id"],
                   ck=["seq"], cols={"id": "int", "seq": "int",
                                     "name": "text", "age": "int"})
    schema.add_table(t)
    eng = StorageEngine(str(tmp_path / "data"), schema,
                        commitlog_sync="batch", **kw)
    return eng, t


def put(eng, t, pk_val, seq, name=None, age=None, ts=None):
    ts = ts or timeutil.now_micros()
    idt = t.columns["id"].cql_type
    m = Mutation(t.id, idt.serialize(pk_val))
    ck = t.serialize_clustering([seq])
    name_id = t.columns["name"].column_id
    age_id = t.columns["age"].column_id
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    m.add(ck, COL_ROW_LIVENESS, b"", b"", ts)
    if name is not None:
        m.add(ck, name_id, b"", t.columns["name"].cql_type.serialize(name), ts)
    if age is not None:
        m.add(ck, age_id, b"", t.columns["age"].cql_type.serialize(age), ts)
    eng.apply(m)
    return ts


def read_rows(eng, t, pk_val):
    idt = t.columns["id"].cql_type
    cfs = eng.store("ks", "users")
    batch = cfs.read_partition(idt.serialize(pk_val))
    return [row_to_dict(t, r) for r in rows_from_batch(t, batch)]


def test_write_read_memtable_only(tmp_path):
    eng, t = new_engine(tmp_path)
    put(eng, t, 1, 1, name="alice", age=30)
    put(eng, t, 1, 2, name="bob")
    rows = read_rows(eng, t, 1)
    assert rows == [{"id": 1, "seq": 1, "name": "alice", "age": 30},
                    {"id": 1, "seq": 2, "name": "bob", "age": None}]
    assert read_rows(eng, t, 999) == []
    eng.close()


def test_flush_and_read(tmp_path):
    eng, t = new_engine(tmp_path)
    for i in range(50):
        put(eng, t, i, 0, name=f"user{i}", age=i)
    cfs = eng.store("ks", "users")
    reader = cfs.flush()
    assert reader is not None and reader.n_cells > 0
    assert cfs.memtable.is_empty
    rows = read_rows(eng, t, 7)
    assert rows == [{"id": 7, "seq": 0, "name": "user7", "age": 7}]
    # update after flush: merged across memtable + sstable
    put(eng, t, 7, 0, age=77)
    rows = read_rows(eng, t, 7)
    assert rows == [{"id": 7, "seq": 0, "name": "user7", "age": 77}]
    eng.close()


def test_overwrite_across_flushes(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "users")
    put(eng, t, 1, 0, name="v1", ts=100)
    cfs.flush()
    put(eng, t, 1, 0, name="v2", ts=200)
    cfs.flush()
    put(eng, t, 1, 0, name="v3", ts=300)
    assert read_rows(eng, t, 1)[0]["name"] == "v3"
    eng.close()


def test_deletes(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "users")
    idt = t.columns["id"].cql_type
    ts1 = put(eng, t, 1, 1, name="a")
    put(eng, t, 1, 2, name="b")
    # row deletion of (1,1)
    m = Mutation(t.id, idt.serialize(1))
    m.add(t.serialize_clustering([1]), 1, b"", b"", ts1 + 10,
          timeutil.now_seconds(), 0, cb.FLAG_ROW_DEL)
    eng.apply(m)
    rows = read_rows(eng, t, 1)
    assert len(rows) == 1 and rows[0]["seq"] == 2
    cfs.flush()
    rows = read_rows(eng, t, 1)
    assert len(rows) == 1 and rows[0]["seq"] == 2
    # partition deletion
    m = Mutation(t.id, idt.serialize(1))
    m.add(b"", 0, b"", b"", timeutil.now_micros(),
          timeutil.now_seconds(), 0, cb.FLAG_PARTITION_DEL)
    eng.apply(m)
    assert read_rows(eng, t, 1) == []
    eng.close()


def test_commitlog_replay(tmp_path):
    eng, t = new_engine(tmp_path)
    for i in range(20):
        put(eng, t, i, 0, name=f"n{i}", age=i)
    # simulate crash: no flush, no clean close of tables
    eng.commitlog.close()

    # new engine over same dir: must recover from commitlog
    schema2 = Schema()
    schema2.create_keyspace("ks")
    t2 = make_table("ks", "users", pk=["id"], ck=["seq"],
                    cols={"id": "int", "seq": "int", "name": "text",
                          "age": "int"})
    t2.id = t.id  # same table identity
    schema2.add_table(t2)
    eng2 = StorageEngine(str(tmp_path / "data"), schema2,
                         commitlog_sync="batch")
    idt = t2.columns["id"].cql_type
    cfs = eng2.store("ks", "users")
    batch = cfs.read_partition(idt.serialize(5))
    rows = [row_to_dict(t2, r) for r in rows_from_batch(t2, batch)]
    assert rows == [{"id": 5, "seq": 0, "name": "n5", "age": 5}]
    # recovered data was flushed; commitlog trimmed
    assert len(cfs.live_sstables()) >= 1
    eng2.close()


def test_flush_threshold_auto(tmp_path):
    eng, t = new_engine(tmp_path, flush_threshold=10_000)
    cfs = eng.store("ks", "users")
    for i in range(500):
        put(eng, t, i, 0, name="x" * 50)
    assert len(cfs.live_sstables()) >= 1  # auto-flushed at least once
    eng.close()


def test_collections_multicell(tmp_path):
    schema = Schema()
    schema.create_keyspace("ks")
    t = make_table("ks", "prefs", pk=["id"],
                   cols={"id": "int", "tags": "map<text, text>"})
    schema.add_table(t)
    eng = StorageEngine(str(tmp_path / "d2"), schema, commitlog_sync="batch")
    idt = t.columns["id"].cql_type
    tags = t.columns["tags"]
    mt = tags.cql_type
    pk = idt.serialize(1)

    def set_tag(k, v, ts):
        m = Mutation(t.id, pk)
        m.add(b"", tags.column_id, mt.key.serialize(k),
              mt.val.serialize(v), ts)
        eng.apply(m)

    set_tag("color", "red", 100)
    set_tag("size", "xl", 110)
    set_tag("color", "blue", 120)          # overwrite one key
    cfs = eng.store("ks", "prefs")
    rows = [row_to_dict(t, r) for r in
            rows_from_batch(t, cfs.read_partition(pk))]
    assert rows == [{"id": 1, "tags": {"color": "blue", "size": "xl"}}]
    cfs.flush()
    # full overwrite: complex deletion + new cells
    m = Mutation(t.id, pk)
    m.add(b"", tags.column_id, b"", b"", 130, timeutil.now_seconds(), 0,
          cb.FLAG_COMPLEX_DEL)
    m.add(b"", tags.column_id, mt.key.serialize("only"),
          mt.val.serialize("one"), 131)
    eng.apply(m)
    rows = [row_to_dict(t, r) for r in
            rows_from_batch(t, cfs.read_partition(pk))]
    assert rows == [{"id": 1, "tags": {"only": "one"}}]
    eng.close()


def test_scan_all(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "users")
    for i in range(30):
        put(eng, t, i, 0, name=f"u{i}")
    cfs.flush()
    for i in range(30, 40):
        put(eng, t, i, 0, name=f"u{i}")
    batch = cfs.scan_all()
    rows = [row_to_dict(t, r) for r in rows_from_batch(t, batch)]
    assert len(rows) == 40
    assert {r["name"] for r in rows} == {f"u{i}" for i in range(40)}
    eng.close()


def test_schema_persisted_across_restart(tmp_path):
    from cassandra_tpu.cql import Session
    d = str(tmp_path / "sp")
    eng = StorageEngine(d, Schema(), commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TYPE addr (street text, zip int)")
    s.execute("CREATE TABLE t (k int, c text, v frozen<addr>, w list<int>, "
              "PRIMARY KEY (k, c)) WITH CLUSTERING ORDER BY (c DESC) "
              "AND gc_grace_seconds = 1234")
    s.execute("INSERT INTO t (k, c, w) VALUES (1, 'x', [1, 2])")
    eng.close()

    # brand-new engine with EMPTY schema: DDL must come back from disk
    eng2 = StorageEngine(d, Schema(), commitlog_sync="batch")
    t = eng2.schema.get_table("ks", "t")
    assert t.params.gc_grace_seconds == 1234
    assert t.clustering_columns[0].reversed is True
    s2 = Session(eng2)
    s2.keyspace = "ks"
    rows = s2.execute("SELECT k, c, w FROM t WHERE k = 1").dicts()
    assert rows == [{"k": 1, "c": "x", "w": [1, 2]}]
    eng2.close()


def test_alter_and_index_persist_across_restart(tmp_path):
    from cassandra_tpu.cql import Session
    d = str(tmp_path / "ap")
    eng = StorageEngine(d, Schema(), commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE t (k int PRIMARY KEY, zz text)")
    s.execute("ALTER TABLE t ADD aa text")   # 'aa' sorts before 'zz'!
    s.execute("CREATE INDEX ON t (zz)")
    s.execute("INSERT INTO t (k, zz, aa) VALUES (1, 'zval', 'aval')")
    eng.close()

    eng2 = StorageEngine(d, Schema(), commitlog_sync="batch")
    s2 = Session(eng2)
    s2.keyspace = "ks"
    row = s2.execute("SELECT k, zz, aa FROM t WHERE k = 1").dicts()[0]
    assert row == {"k": 1, "zz": "zval", "aa": "aval"}   # ids stable
    # index restored and functional
    rs = s2.execute("SELECT k FROM t WHERE zz = 'zval'")
    assert rs.rows == [(1,)]
    eng2.close()


def test_compressed_commitlog_roundtrip(tmp_path):
    """Compressed segments (db/commitlog/CompressedSegment.java role):
    records written through an LZ4 commitlog replay bit-identically,
    torn tails still terminate cleanly, and the on-disk segment is
    smaller than the raw payload volume for compressible writes."""
    import os
    import uuid

    from cassandra_tpu.storage.commitlog import CommitLog
    from cassandra_tpu.storage.mutation import Mutation

    d = str(tmp_path / "cl")
    cl = CommitLog(d, sync_mode="batch", compression="LZ4Compressor")
    tid = uuid.uuid4()
    written = []
    for i in range(200):
        m = Mutation(tid, f"pk{i % 8}".encode())
        m.add(b"", 8, b"", (b"value-%d" % i) * 40, ts=i)
        cl.add(m)
        written.append(m)
    cl.sync()
    replayed = list(cl.replay())
    assert len(replayed) == 200
    for (pos, got), want in zip(replayed, written):
        assert got.serialize() == want.serialize()
    # compressible payloads: stored bytes well under raw volume
    raw = sum(len(m.serialize()) + 12 for m in written)
    stored = sum(os.path.getsize(os.path.join(d, fn))
                 for fn in os.listdir(d) if fn.endswith(".log"))
    # preallocation keeps st_size at the append point, so this compares
    # actual written extents
    assert stored < raw * 0.6, (stored, raw)
    # torn tail: truncate mid-record, replay stops cleanly
    seg = os.path.join(d, f"commitlog-{cl.segment_ids()[-1]}.log")
    sz = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(sz - 7)
    n = len(list(cl.replay()))
    assert n == 199
    cl.close()
