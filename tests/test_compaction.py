"""Compaction tests: STCS/LCS/TWCS selection, task correctness (content
preserved, tombstones purged per gc/overlap rules), lifecycle crash safety.
(Reference model: CompactionsPurgeTest, CompactionTaskTest,
LeveledCompactionStrategyTest, TimeWindowCompactionStrategyTest.)"""
import os
import time

import numpy as np
import pytest

from cassandra_tpu.compaction import CompactionManager, get_strategy
from cassandra_tpu.compaction.task import CompactionTask
from cassandra_tpu.schema import (COL_ROW_LIVENESS, Schema, TableParams,
                                  make_table)
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.lifecycle import replay_directory
from cassandra_tpu.storage.mutation import Mutation
from cassandra_tpu.storage.rows import row_to_dict, rows_from_batch
from cassandra_tpu.storage.sstable import Descriptor
from cassandra_tpu.utils import timeutil


def new_engine(tmp_path, compaction=None, gc_grace=864000):
    schema = Schema()
    schema.create_keyspace("ks")
    params = TableParams(gc_grace_seconds=gc_grace)
    if compaction:
        params.compaction = compaction
    t = make_table("ks", "t", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "text"},
                   params=params)
    schema.add_table(t)
    eng = StorageEngine(str(tmp_path / "data"), schema,
                        commitlog_sync="batch")
    return eng, t, eng.store("ks", "t")


def put(eng, t, p, c, v, ts=None):
    m = Mutation(t.id, t.columns["id"].cql_type.serialize(p))
    ck = t.serialize_clustering([c])
    ts = ts or timeutil.now_micros()
    m.add(ck, COL_ROW_LIVENESS, b"", b"", ts)
    m.add(ck, t.columns["v"].column_id, b"",
          t.columns["v"].cql_type.serialize(v), ts)
    eng.apply(m)


def read_all(t, cfs):
    return sorted(
        (row_to_dict(t, r) for r in rows_from_batch(t, cfs.scan_all())),
        key=lambda r: (r["id"], r["c"]))


def test_stcs_selection_and_merge(tmp_path):
    eng, t, cfs = new_engine(tmp_path)
    # 4 flushes of similar size -> one STCS bucket
    for gen in range(4):
        for p in range(20):
            put(eng, t, p, gen, f"g{gen}-p{p}")
        cfs.flush()
    assert len(cfs.live_sstables()) == 4
    strat = get_strategy(cfs)
    task = strat.next_background_task()
    assert task is not None and len(task.inputs) == 4
    stats = task.execute()
    assert stats["outputs"] == 1
    assert len(cfs.live_sstables()) == 1
    rows = read_all(t, cfs)
    assert len(rows) == 80
    assert {r["v"] for r in rows} == {f"g{g}-p{p}" for g in range(4)
                                      for p in range(20)}
    # old files gone from disk
    assert len(Descriptor.list_in(cfs.directory)) == 1
    eng.close()


def test_overwrites_deduplicated(tmp_path):
    eng, t, cfs = new_engine(tmp_path)
    for gen in range(4):
        for p in range(10):
            put(eng, t, p, 0, f"v{gen}", ts=1000 + gen)
        cfs.flush()
    task = get_strategy(cfs).major_task()
    stats = task.execute()
    rows = read_all(t, cfs)
    assert len(rows) == 10 and all(r["v"] == "v3" for r in rows)
    # 4 versions collapsed to 1
    assert stats["cells_written"] < stats["cells_read"]
    eng.close()


def test_tombstone_purge_rules(tmp_path):
    eng, t, cfs = new_engine(tmp_path, gc_grace=0)  # tombstones purgeable now
    idt = t.columns["id"].cql_type
    put(eng, t, 1, 0, "doomed", ts=100)
    cfs.flush()
    # delete the row
    m = Mutation(t.id, idt.serialize(1))
    m.add(t.serialize_clustering([0]), 1, b"", b"", 200,
          timeutil.now_seconds() - 10, 0, cb.FLAG_ROW_DEL)
    eng.apply(m)
    cfs.flush()
    assert len(cfs.live_sstables()) == 2
    # major compaction includes both sstables: tombstone + shadowed data
    # both disappear (gc_grace=0, no overlap outside the compaction)
    get_strategy(cfs).major_task().execute()
    assert read_all(t, cfs) == []
    live = cfs.live_sstables()
    assert sum(s.n_cells for s in live) == 0 or len(live) == 0
    eng.close()


def test_tombstone_kept_when_overlap_exists(tmp_path):
    eng, t, cfs = new_engine(tmp_path, gc_grace=0)
    idt = t.columns["id"].cql_type
    put(eng, t, 1, 0, "old", ts=100)
    cfs.flush()                      # sstable A: data
    m = Mutation(t.id, idt.serialize(1))
    m.add(t.serialize_clustering([0]), 1, b"", b"", 200,
          timeutil.now_seconds() - 10, 0, cb.FLAG_ROW_DEL)
    eng.apply(m)
    cfs.flush()                      # sstable B: tombstone
    a, b = cfs.live_sstables()
    # compact ONLY the tombstone sstable: A still holds shadowed data, so
    # the tombstone must survive (CompactionController.shouldPurge)
    tomb = b if b.n_tombstones else a
    CompactionTask(cfs, [tomb]).execute()
    assert read_all(t, cfs) == []    # row still deleted
    live = cfs.live_sstables()
    assert any(s.n_tombstones for s in live), "tombstone wrongly purged"
    eng.close()


def test_lcs_levels(tmp_path):
    eng, t, cfs = new_engine(
        tmp_path,
        compaction={"class": "LeveledCompactionStrategy",
                    "sstable_size_in_mb": 1, "l0_threshold": 4})
    for gen in range(4):
        for p in range(30):
            put(eng, t, p + gen * 30, 0, "x" * 100)
        cfs.flush()
    strat = get_strategy(cfs)
    task = strat.next_background_task()
    assert task is not None and task.level == 1
    task.execute()
    assert all(s.level == 1 for s in cfs.live_sstables())
    assert read_all(t, cfs) and len(read_all(t, cfs)) == 120
    eng.close()


def test_twcs_windows(tmp_path):
    eng, t, cfs = new_engine(
        tmp_path,
        compaction={"class": "TimeWindowCompactionStrategy",
                    "compaction_window_unit": "HOURS",
                    "compaction_window_size": 1})
    now_us = timeutil.now_micros()
    hour = 3600 * 1_000_000
    # two sstables in an OLD window, two in the current window
    for i, ts in enumerate([now_us - 5 * hour, now_us - 5 * hour + 1000]):
        put(eng, t, i, 0, f"old{i}", ts=ts)
        cfs.flush()
    for i, ts in enumerate([now_us, now_us + 1000]):
        put(eng, t, 10 + i, 0, f"new{i}", ts=ts)
        cfs.flush()
    strat = get_strategy(cfs)
    task = strat.next_background_task()
    assert task is not None
    # must pick the old window (2 sstables there, below min_threshold=4
    # in the current window)
    wins = {strat._window_of(s) for s in task.inputs}
    assert len(wins) == 1 and wins.pop() != max(
        strat._window_of(s) for s in cfs.live_sstables())
    task.execute()
    assert len(read_all(t, cfs)) == 4
    eng.close()


def test_manager_auto_trigger(tmp_path):
    eng, t, cfs = new_engine(tmp_path)
    mgr = CompactionManager()
    mgr.register(cfs)
    for gen in range(4):
        for p in range(10):
            put(eng, t, p, gen, f"{gen}")
        cfs.flush()
    assert mgr.run_pending() >= 1
    assert len(cfs.live_sstables()) == 1
    assert mgr.completed and mgr.completed[0]["inputs"] == 4
    eng.close()


def test_lifecycle_crash_rollback(tmp_path):
    eng, t, cfs = new_engine(tmp_path)
    for gen in range(2):
        put(eng, t, gen, 0, f"v{gen}")
        cfs.flush()
    # simulate a crash mid-compaction: txn log without COMMIT + a stray
    # new-generation file
    gen = Descriptor.next_generation(cfs.directory)
    stray = os.path.join(cfs.directory, f"ca-{gen}-Data.db")
    open(stray, "wb").write(b"partial")
    with open(os.path.join(cfs.directory, "txn-deadbeef.log"), "w") as f:
        f.write(f"ADD {gen}\n")
    replay_directory(cfs.directory)
    assert not os.path.exists(stray)
    assert len(Descriptor.list_in(cfs.directory)) == 2  # originals intact
    eng.close()


def test_lifecycle_crash_rollforward(tmp_path):
    eng, t, cfs = new_engine(tmp_path)
    put(eng, t, 1, 0, "a")
    cfs.flush()
    old_gen = cfs.live_sstables()[0].desc.generation
    # committed txn whose REMOVE deletions didn't finish
    with open(os.path.join(cfs.directory, "txn-cafebabe.log"), "w") as f:
        f.write(f"REMOVE {old_gen}\nCOMMIT\n")
    replay_directory(cfs.directory)
    assert Descriptor.list_in(cfs.directory) == []  # rolled forward
    eng.close()


def test_engine_wires_background_compaction(tmp_path):
    """The engine itself owns a CompactionManager: flushes enqueue the
    store (no per-test manager needed), run_pending() drains it, and
    nodetool's throughput knobs act on the live limiter."""
    from cassandra_tpu.tools import nodetool

    eng, t, cfs = new_engine(tmp_path)
    try:
        for gen in range(4):
            for p in range(20):
                put(eng, t, p, gen, f"g{gen}-p{p}")
            cfs.flush()
        assert len(cfs.live_sstables()) == 4
        assert eng.compactions.run_pending() >= 1     # flush enqueued it
        assert len(cfs.live_sstables()) < 4
        assert len(read_all(t, cfs)) == 80    # all rows survive the merge

        nodetool.setcompactionthroughput(eng, 16)
        assert nodetool.getcompactionthroughput(eng) == \
            {"compaction_throughput_mib": 16}
        assert eng.compactions.limiter.rate == 16 * 2**20
        nodetool.setcompactionthroughput(eng, 0)      # unthrottle
        assert eng.compactions.limiter.rate == 0
    finally:
        eng.close()
