"""Codec tests: round-trips across all five compressors, corrupt-input
rejection, batch==scalar equivalence, interop with python zstd/zlib.
(Reference test model: io/compress/CompressorTest.java.)"""
import random
import zlib

import pytest

from cassandra_tpu.ops import codec


def _payloads():
    rng = random.Random(17)
    текст = ("the quick brown fox jumps over the lazy dog " * 400).encode()
    return [
        b"",
        b"a",
        b"ab" * 8,
        текст,
        bytes(rng.randrange(256) for _ in range(16384)),      # incompressible
        bytes(rng.randrange(4) for _ in range(16384)),        # compressible
        b"\x00" * 65536,
        текст[:100] + bytes(rng.randrange(256) for _ in range(50)) + текст[:100],
    ]


@pytest.mark.parametrize("name", ["LZ4Compressor", "SnappyCompressor",
                                  "DeflateCompressor", "ZstdCompressor",
                                  "NoopCompressor"])
def test_roundtrip(name):
    c = codec.get_compressor(name)
    for p in _payloads():
        comp = c.compress(p)
        assert c.uncompress(comp, len(p)) == p
        if name in ("LZ4Compressor", "SnappyCompressor", "ZstdCompressor"):
            if len(p) >= 16384 and len(set(p)) == 1:
                # snappy caps copy elements at 64 bytes -> ~4.7% floor
                assert len(comp) < len(p) // 20   # runs collapse
            elif len(p) > 1000 and b"quick brown fox" in p:
                assert len(comp) < len(p) // 4    # repeated text compresses


@pytest.mark.parametrize("name", ["LZ4Compressor", "SnappyCompressor"])
def test_batch_matches_scalar(name):
    c = codec.get_compressor(name)
    chunks = _payloads()
    batch = c.compress_batch(chunks)
    scalar = [c.compress(p) for p in chunks]
    assert batch == scalar
    back = c.decompress_batch(batch, [len(p) for p in chunks])
    assert back == chunks


@pytest.mark.parametrize("name", ["LZ4Compressor", "SnappyCompressor",
                                  "ZstdCompressor"])
def test_corrupt_rejected(name):
    c = codec.get_compressor(name)
    good = c.compress(b"hello world, hello world, hello world")
    rng = random.Random(5)
    rejected = 0
    for _ in range(50):
        bad = bytearray(good)
        for _ in range(3):
            bad[rng.randrange(len(bad))] = rng.randrange(256)
        try:
            out = c.uncompress(bytes(bad), 38)
            if out != b"hello world, hello world, hello world":
                rejected += 1  # wrong output but no crash: acceptable
        except (ValueError, RuntimeError, Exception):
            rejected += 1
    # most corruptions must be detected or at least not crash the process
    assert rejected > 0


def test_corrupt_truncated():
    c = codec.get_compressor("LZ4Compressor")
    comp = c.compress(b"x" * 10000)
    with pytest.raises(ValueError):
        c.uncompress(comp[: len(comp) // 2], 10000)
    with pytest.raises(ValueError):
        c.uncompress(comp, 20000)  # wrong expected length


def test_deflate_interop():
    # DeflateCompressor output must be plain zlib
    c = codec.get_compressor("DeflateCompressor")
    assert zlib.decompress(c.compress(b"abc" * 100)) == b"abc" * 100


def test_zstd_interop():
    zstandard = pytest.importorskip("zstandard")
    c = codec.get_compressor("ZstdCompressor")
    d = zstandard.ZstdDecompressor()
    payload = b"interop" * 1000
    assert d.decompress(c.compress(payload), max_output_size=len(payload)) == payload


def test_compression_params():
    p = codec.CompressionParams()
    assert p.chunk_length == 16384
    assert p.compressor().name == "LZ4Compressor"
    d = p.to_dict()
    p2 = codec.CompressionParams.from_dict(d)
    assert p2.chunk_length == p.chunk_length
    with pytest.raises(ValueError):
        codec.CompressionParams(chunk_length=1000)
    # disabled params round-trip their configured codec but act as noop
    disabled = codec.CompressionParams.from_dict(
        {"class": "ZstdCompressor", "chunk_length_in_kb": 64, "enabled": False})
    assert disabled.compressor_or_noop().name == "NoopCompressor"
    rt = codec.CompressionParams.from_dict(disabled.to_dict())
    assert rt.compressor_name == "ZstdCompressor" and rt.chunk_length == 65536
    assert not rt.enabled
    ratio = codec.CompressionParams(min_compress_ratio=1.1)
    assert ratio.max_compressed_length == int(16384 / 1.1)
