"""Commitlog: segmented durable WAL with CRC-framed records and replay.

Reference counterpart: db/commitlog/CommitLog.java:300 (add),
CommitLogSegment, AbstractCommitLogSegmentManager (segment rotation,
per-table dirty tracking), CommitLogReplayer (boot replay). Sync
strategies: 'periodic' (buffered, background fsync every N ms) and 'batch'
(fsync before ack) — conf/cassandra.yaml commitlog_sync options.

Record frame: [u32 length][u32 crc32-of-payload][payload]. A zero length
or short read terminates replay of a segment (torn tail after crash).
"""
from __future__ import annotations

import os
import re
import struct
import threading
import zlib

from ..utils import fsutil
from .mutation import Mutation

_SEG_RE = re.compile(r"^commitlog-(\d+)\.log$")


class CommitLogPosition(tuple):
    """(segment_id, offset) — totally ordered."""
    def __new__(cls, segment_id: int, offset: int):
        return super().__new__(cls, (segment_id, offset))

    @property
    def segment_id(self):
        return self[0]

    @property
    def offset(self):
        return self[1]


_ENC_MAGIC = b"CTPUCLE1"   # encrypted segment: magic + u32 key id + nonce16
_ENC_HDR = len(_ENC_MAGIC) + 4 + 16
# compressed segment (db/commitlog/CompressedSegment.java role): magic +
# u8 codec-name length + codec name. Records in such a segment use the
# 12-byte frame [u32 stored_len][u32 crc][u32 raw_len]; raw_len ==
# stored_len marks an incompressible record stored raw. Composes with
# encryption as compress-then-encrypt (the reference's EncryptedSegment
# also compresses before encrypting); the CRC covers the stored bytes.
_COMP_MAGIC = b"CTPUCLC1"


class CommitLog:
    def __init__(self, directory: str, segment_size: int = 32 * 1024 * 1024,
                 sync_mode: str = "periodic", sync_period_ms: int = 1000,
                 archive_dir: str | None = None, encrypt: bool = False,
                 compression: str | None = None):
        """archive_dir: finished segments are copied there on rotation
        and at close (CommitLogArchiver role — the restore half is
        replay_archived / StorageEngine.restore_point_in_time).
        encrypt: segments carry an AES-CTR header and record payloads
        are keystream-XORed at their file offset
        (db/commitlog/EncryptedSegment.java role; CRCs cover ciphertext)."""
        self.directory = directory
        self.segment_size = segment_size
        self.sync_mode = sync_mode
        self.sync_period_ms = sync_period_ms
        self.archive_dir = archive_dir
        self.encrypt = encrypt
        self.compression = compression or None
        self._compressor = None
        if self.compression:
            from ..ops.codec import get_compressor
            self._compressor = get_compressor(self.compression)
        if archive_dir:
            os.makedirs(archive_dir, exist_ok=True)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        existing = self.segment_ids()
        self._seg_id = (existing[-1] + 1) if existing else 1
        self._file = None
        self._seg_enc = None      # (key_id, nonce) of the open segment
        # archiver worker: rotation must not stall writers on a 32MB
        # copy+fsync (the reference archives asynchronously too); a
        # segment awaiting archive is protected from deletion
        self._archive_q: list[int] = []
        self._archiving: set[int] = set()
        self._archive_ev = threading.Event()
        self._archive_thread = None
        if archive_dir:
            # crash recovery: segments already on disk were finished by
            # the crash and were never archived (there was no clean
            # close) — archive them NOW, before boot replay flushes and
            # deletes them, or PITR silently loses the tail
            for seg in existing:
                self._archive(seg)
            self._archive_thread = threading.Thread(
                target=self._archive_loop, daemon=True,
                name="commitlog-archiver")
            self._archive_thread.start()
        self._open_segment()
        # dirty tracking: segment -> set of table ids with unflushed writes
        self._dirty: dict[int, set] = {}
        self._stop = threading.Event()
        self._syncer = None
        if sync_mode == "periodic":
            self._syncer = threading.Thread(target=self._sync_loop,
                                            daemon=True)
            self._syncer.start()

    # ------------------------------------------------------------ segments

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.directory, f"commitlog-{seg_id}.log")

    def segment_ids(self) -> list[int]:
        out = []
        for fn in os.listdir(self.directory):
            m = _SEG_RE.match(fn)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _open_segment(self) -> None:
        prev = None
        if self._file:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
            prev = self._seg_id - 1
        self._file = open(self._seg_path(self._seg_id), "ab")
        self._seg_comp = None
        if prev is not None and self.archive_dir:
            # async: the rotated segment is immutable; the worker copies
            # it off the write path (deletion waits for the archive)
            self._archiving.add(prev)
            self._archive_q.append(prev)
            self._archive_ev.set()
        if self.encrypt:
            from . import encryption as enc_mod
            ctx = enc_mod.get_context()
            if ctx is None:
                raise enc_mod.EncryptionError(
                    "commitlog encryption requires an EncryptionContext")
            if self._file.tell() == 0:
                kid = ctx.current_key_id
                nonce = ctx.new_nonce()
                self._file.write(_ENC_MAGIC + kid.to_bytes(4, "little")
                                 + nonce)
                self._file.flush()
                self._seg_enc = (kid, nonce)
            else:   # restart onto a partially-written encrypted segment
                with open(self._seg_path(self._seg_id), "rb") as f:
                    hdr = f.read(_ENC_HDR)
                if not hdr.startswith(_ENC_MAGIC):
                    raise enc_mod.EncryptionError(
                        "existing active segment is not encrypted; "
                        "rotate before enabling encryption")
                self._seg_enc = (int.from_bytes(hdr[8:12], "little"),
                                 hdr[12:28])
        if self._compressor is not None:
            if self._file.tell() == 0 or (
                    self.encrypt and self._file.tell() == _ENC_HDR):
                name = self.compression.encode()
                self._file.write(_COMP_MAGIC + bytes([len(name)]) + name)
                self._file.flush()
            self._seg_comp = self._compressor
        # reserve the whole segment's blocks up front (KEEP_SIZE: st_size
        # stays at the append point so replay's EOF/torn-tail detection is
        # unaffected). The reference pre-creates fixed-size segments for
        # the same reason (CommitLogSegment); on this box extending
        # writes are ~75x slower than writes into reserved blocks.
        fsutil.preallocate_keep_size(
            self._file.fileno(), self._file.tell(),
            max(0, self.segment_size - self._file.tell()))

    # ----------------------------------------------------------------- add

    def add(self, mutation: Mutation) -> CommitLogPosition:
        """Append a mutation; returns its position. With sync_mode='batch'
        the record is durable when this returns (CommitLog.add:300)."""
        payload = mutation.serialize()
        with self._lock:
            if self._file.tell() + len(payload) + 12 > self.segment_size:
                self._seg_id += 1
                self._open_segment()
            pos = CommitLogPosition(self._seg_id, self._file.tell())
            raw_len = len(payload)
            if self._seg_comp is not None:
                c = self._seg_comp.compress(payload)
                if len(c) < raw_len:
                    payload = c
            if self._seg_enc is not None:
                from . import encryption as enc_mod
                kid, nonce = self._seg_enc
                hdr = 12 if self._seg_comp is not None else 8
                payload = enc_mod.get_context().xor_at(
                    kid, nonce, pos.offset + hdr, payload)
            if self._seg_comp is not None:
                frame = struct.pack("<III", len(payload),
                                    zlib.crc32(payload), raw_len) + payload
            else:
                frame = struct.pack("<II", len(payload),
                                    zlib.crc32(payload)) + payload
            self._file.write(frame)
            self._dirty.setdefault(self._seg_id, set()).add(mutation.table_id)
            if self.sync_mode == "batch":
                self._file.flush()
                os.fsync(self._file.fileno())
        return pos

    def sync(self) -> None:
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())

    def _sync_loop(self) -> None:
        while not self._stop.wait(self.sync_period_ms / 1000.0):
            try:
                self.sync()
            except (OSError, ValueError):
                return

    # -------------------------------------------------------------- replay

    def replay(self):
        """Yield (position, Mutation) for every intact record on disk
        (CommitLogReplayer semantics: stop a segment at the first torn
        record)."""
        for seg_id in self.segment_ids():
            yield from self._replay_file(self._seg_path(seg_id), seg_id)

    @staticmethod
    def _replay_file(path: str, seg_id: int):
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        enc = None
        comp = None
        if data.startswith(_ENC_MAGIC):
            from . import encryption as enc_mod
            ctx = enc_mod.get_context()
            if ctx is None:
                raise enc_mod.EncryptionError(
                    f"{path} is encrypted but no EncryptionContext is "
                    f"installed")
            enc = (ctx, int.from_bytes(data[8:12], "little"),
                   data[12:_ENC_HDR])
            pos = _ENC_HDR
        if data[pos:pos + len(_COMP_MAGIC)] == _COMP_MAGIC:
            from ..ops.codec import get_compressor
            nlen = data[pos + len(_COMP_MAGIC)]
            name = data[pos + len(_COMP_MAGIC) + 1:
                        pos + len(_COMP_MAGIC) + 1 + nlen].decode()
            comp = get_compressor(name)
            pos += len(_COMP_MAGIC) + 1 + nlen
        hdr = 12 if comp is not None else 8
        while pos + hdr <= len(data):
            if comp is not None:
                length, crc, raw_len = struct.unpack_from("<III", data,
                                                          pos)
            else:
                length, crc = struct.unpack_from("<II", data, pos)
                raw_len = length
            if length == 0 or pos + hdr + length > len(data):
                break  # torn tail
            payload = data[pos + hdr: pos + hdr + length]
            if zlib.crc32(payload) != crc:
                break  # corrupt tail
            if enc is not None:
                ctx, kid, nonce = enc
                payload = ctx.xor_at(kid, nonce, pos + hdr, payload)
            if comp is not None and length < raw_len:
                payload = comp.uncompress(bytes(payload), raw_len)
            yield CommitLogPosition(seg_id, pos), \
                Mutation.deserialize(bytes(payload))
            pos += hdr + length

    # ------------------------------------------------------------ archive

    def _archive(self, seg_id: int) -> None:
        """Copy a FINISHED (rotated/closed) segment to the archive
        (CommitLogArchiver.java:54 role; a directory copy stands in for
        the archive_command hook)."""
        if not self.archive_dir:
            return
        src = self._seg_path(seg_id)
        if not os.path.exists(src):
            return
        dst = os.path.join(self.archive_dir, os.path.basename(src))
        import shutil
        tmp = dst + ".tmp"
        shutil.copy2(src, tmp)
        with open(tmp, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def _archive_loop(self) -> None:
        while True:
            self._archive_ev.wait()
            self._archive_ev.clear()
            while True:
                with self._lock:
                    if not self._archive_q:
                        break
                    seg = self._archive_q.pop(0)
                try:
                    self._archive(seg)
                except OSError:
                    pass
                with self._lock:
                    self._archiving.discard(seg)

    def _deletable(self, seg_id: int) -> bool:
        """A segment pending archive must not be deleted: its PITR copy
        hasn't landed yet."""
        return seg_id not in self._archiving

    @classmethod
    def replay_archived(cls, archive_dir: str):
        """Yield (position, Mutation) from archived segments in order —
        the restore half of PITR (CommitLogArchiver restore_directories
        + restore_point_in_time)."""
        segs = []
        for fn in os.listdir(archive_dir):
            m = _SEG_RE.match(fn)
            if m:
                segs.append((int(m.group(1)), fn))
        for seg_id, fn in sorted(segs):
            yield from cls._replay_file(os.path.join(archive_dir, fn),
                                        seg_id)

    # ----------------------------------------------------- flush lifecycle

    def discard_completed(self, table_id, upto: CommitLogPosition) -> None:
        """Mark a table's writes flushed up to `upto`; delete segments no
        table dirties anymore (CommitLog.discardCompletedSegments)."""
        with self._lock:
            # a segment at/after the flush point may hold post-switch writes
            # for this table, so only older segments become clean
            for seg_id in list(self._dirty):
                if seg_id < upto.segment_id:
                    self._dirty[seg_id].discard(table_id)
                    if not self._dirty[seg_id] and seg_id != self._seg_id \
                            and self._deletable(seg_id):
                        try:
                            os.remove(self._seg_path(seg_id))
                        except FileNotFoundError:
                            pass
                        del self._dirty[seg_id]

    def forget_table(self, table_id) -> None:
        """A dropped table's writes no longer pin segments."""
        with self._lock:
            for seg_id in list(self._dirty):
                self._dirty[seg_id].discard(table_id)
                if not self._dirty[seg_id] and seg_id != self._seg_id \
                        and self._deletable(seg_id):
                    try:
                        os.remove(self._seg_path(seg_id))
                    except FileNotFoundError:
                        pass
                    del self._dirty[seg_id]

    def current_position(self) -> CommitLogPosition:
        with self._lock:
            return CommitLogPosition(self._seg_id, self._file.tell())

    def delete_segments_before(self, seg_id: int) -> None:
        for s in self.segment_ids():
            if s < seg_id and self._deletable(s):
                try:
                    os.remove(self._seg_path(s))
                except FileNotFoundError:
                    pass
                self._dirty.pop(s, None)

    def close(self) -> None:
        self._stop.set()
        if self._syncer:
            self._syncer.join(timeout=2)
        # drain pending async archives BEFORE the final archive so the
        # directory copy is complete when close() returns
        deadline = 50
        while deadline and self._archiving:
            import time as _t
            _t.sleep(0.1)
            deadline -= 1
        with self._lock:
            if self._file and not self._file.closed:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
                # a cleanly-closed active segment is archivable too
                self._archive(self._seg_id)
