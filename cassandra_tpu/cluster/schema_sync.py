"""Distributed schema agreement — the Paxos-backed epoch log (TCM).

Reference counterpart: tcm/ClusterMetadata.java:81 + the log-based
transformation model (every metadata change is an ordered log entry;
replicas converge by applying the same entries in the same order),
committed through a Paxos-backed processor on a CMS replica group
(tcm/PaxosBackedProcessor.java:57, tcm/Commit.java). Scaled to this
framework: the replicated unit is the DDL STATEMENT TEXT (or a
#topology transformation), ordered by a per-cluster epoch counter.

Commit model (cluster/cms.py): every epoch slot is decided by
single-decree Paxos over the CMS replica set (the min(3) lowest-named
endpoints). A CMS member coordinates directly; any other node forwards
(SCHEMA_FORWARD) to a live CMS member and applies the acked entry, so
the statement is visible locally when execute() returns. A minority
partition CANNOT commit (MetadataUnavailable) — no fork is possible;
a proposer that loses a slot to a concurrent commit applies the winner
and retries its own statement at the next slot.

  - Learn paths: CMS members apply at Paxos-commit time; all peers get
    SCHEMA_PUSH(epoch, entry); a node seeing a future epoch pulls the
    gap (SCHEMA_PULL, async — the response callback runs on the same
    dispatch thread later; nothing here may block on a response).
  - A (re)starting node replays its persisted log, then pulls anything
    newer from the first live peer.
  - Same-epoch conflicts cannot be produced by CMS commits; the
    deterministic winner rule below survives only as tolerance for
    logs predating the CMS (and screams into stderr if it ever fires).

Enabled for per-process schemas (TCP deployments and per-node-schema
test rigs); LocalCluster shares one Schema object in-process and needs
no sync.
"""
from __future__ import annotations

import json
import os
import sys
import threading

from .messaging import Verb


DDL_STATEMENTS = {
    "CreateKeyspaceStatement", "CreateTableStatement",
    "CreateIndexStatement", "CreateTypeStatement", "CreateViewStatement",
    "CreateFunctionStatement", "CreateAggregateStatement",
    "CreateTriggerStatement", "DropTriggerStatement",
    "DropStatement", "AlterTableStatement",
    # NOT TruncateStatement: truncation is a DATA operation with its own
    # cluster fan-out (TRUNCATE_REQ); replaying it from the schema log on
    # a late-joining node would wipe rows written after the original
}


class SchemaForwardError(ValueError):
    """The designated coordinator rejected the DDL (e.g. parse or
    execution error there) — surfaced to the issuing session."""


TOPOLOGY_PREFIX = "#topology "


def apply_topology_to_ring(ring, extra: dict) -> None:
    """Apply one topology transformation to a Ring. The single
    definition both the epoch-log path (TCP clusters) and the shared-ring
    path (LocalCluster) go through — reference
    tcm/transformations/* applied to ClusterMetadata's tokenMap."""
    from .ring import Endpoint

    op = extra["op"]
    nd = extra.get("node") or {}
    ep = Endpoint(nd["name"], nd.get("dc", "dc1"), nd.get("rack", "rack1"),
                  nd.get("host", "127.0.0.1"), int(nd.get("port", 0)))

    def existing(name: str):
        for e in ring.endpoints:
            if e.name == name:
                return e
        raise ValueError(f"endpoint {name} not in ring")

    tokens = [int(t) for t in extra.get("tokens") or []]
    if op == "register":
        ring.add_node(ep, tokens)
    elif op == "start_join":
        ring.add_pending(ep, tokens)
    elif op == "finish_join":
        ring.promote_pending(ep)
    elif op == "abort_join":
        ring.cancel_pending(ep)
    elif op == "leave":
        ring.remove_node(existing(nd["name"]))
    elif op == "start_move":
        ring.start_move(existing(nd["name"]), tokens)
    elif op == "finish_move":
        ring.finish_move(existing(nd["name"]),
                         [int(t) for t in extra["old_tokens"]])
    elif op == "abort_move":
        ring.abort_move(existing(nd["name"]))
    elif op == "start_replace":
        ring.start_replace(ep, existing(extra["target"]))
    elif op == "finish_replace":
        ring.finish_replace(ep)
    elif op == "abort_replace":
        ring.cancel_replace(ep)
    else:
        raise ValueError(f"unknown topology op {op!r}")


def emit_topology_event(node, extra: dict) -> None:
    """Driver-facing TOPOLOGY_CHANGE for a committed transformation
    (transport Event.TopologyChange role). Only the COMMIT points of
    multi-step sequences emit — drivers see the ownership flip, not the
    intermediate pending states."""
    op = extra["op"]
    nd = extra.get("node") or {}
    info = {"host": nd.get("host", "127.0.0.1"),
            "port": int(nd.get("port", 0))}
    change = {"register": "NEW_NODE", "finish_join": "NEW_NODE",
              "finish_replace": "NEW_NODE", "leave": "REMOVED_NODE",
              "finish_move": "MOVED_NODE"}.get(op)
    if change is None:
        return
    emit = getattr(node, "emit_event", None)
    if emit is not None:
        emit("TOPOLOGY_CHANGE", {"change": change, **info})


class SchemaSync:
    FORWARD_TIMEOUT = 5.0
    # pulls re-fetch a window of already-seen epochs so a conflict
    # winner whose one-way push was lost still reconciles on the next
    # pull (startup catch-up or any gap pull) via the winner rule
    PULL_OVERLAP = 8

    def __init__(self, node, directory: str):
        self.node = node
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "schema_log.jsonl")
        self.epoch = 0
        self._lock = threading.RLock()
        self._load()
        # statements THIS node already executed locally and is currently
        # committing through the CMS — learn() must log, not re-apply,
        # them (the Paxos COMMIT self-delivery arrives before the
        # coordination path's own learn call)
        self._inflight_local: set = set()
        from .cms import CMSService
        self.cms = CMSService(node, self, directory)
        ms = node.messaging
        ms.register_handler(Verb.SCHEMA_PUSH, self._handle_push)
        ms.register_handler(Verb.SCHEMA_PULL, self._handle_pull)
        ms.register_handler(Verb.SCHEMA_FORWARD, self._handle_forward)

    # ------------------------------------------------------------- log --

    def _load(self) -> None:
        # the file is durability; _entries (epoch -> LAST record at that
        # epoch, i.e. the conflict winner) is the read path — handlers
        # consult it under _lock, so lookups must not re-read the file
        self._entries: dict[int, tuple] = {}
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    break               # torn tail
                e = int(rec["epoch"])
                self._entries[e] = (e, rec["query"], rec.get("keyspace"),
                                    rec.get("extra") or {},
                                    rec.get("coord"))
                self.epoch = max(self.epoch, e)

    def _append(self, epoch: int, query: str, keyspace, extra,
                coord: str | None = None) -> None:
        coord = coord or self.node.endpoint.name
        with open(self.path, "a") as f:
            f.write(json.dumps({"epoch": epoch, "query": query,
                                "keyspace": keyspace, "extra": extra,
                                "coord": coord}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._entries[epoch] = (epoch, query, keyspace, extra or {},
                                coord)

    def entries_after(self, epoch: int) -> list[tuple]:
        """Entries newer than `epoch`, ONE record per epoch: an epoch
        rewritten by conflict resolution keeps only its LAST (winning)
        record, so pullers apply exactly what push-path nodes applied."""
        with self._lock:
            return [self._entries[e] for e in sorted(self._entries)
                    if e > epoch]

    def _entry_at(self, epoch: int):
        """Last (i.e. winning) record logged at `epoch`, or None."""
        return self._entries.get(epoch)

    def entry_at(self, epoch: int):
        """Thread-safe committed-entry lookup (CMS prepare fast path)."""
        with self._lock:
            return self._entries.get(epoch)

    def learn(self, slot: int, ddict: dict,
              skip_apply: bool = False) -> None:
        """Apply a Paxos-DECIDED entry if it is next in sequence.
        skip_apply: the entry is OUR OWN statement, already executed
        locally by the coordination path — log it without re-applying.
        A stale slot is a no-op; a gap is left for push/pull catch-up
        (the decided value will arrive again there)."""
        with self._lock:
            if slot != self.epoch + 1:
                return
            q, k, x, c = ddict["q"], ddict["k"], ddict.get("x") or {}, \
                ddict.get("c")
            if c == self.node.endpoint.name and q in self._inflight_local:
                skip_apply = True
            if skip_apply:
                self.epoch = slot
                self._append(slot, q, k, x, coord=c)
            else:
                self._apply_entry(slot, q, k, x, coord=c)

    # ------------------------------------------------------- application --

    def _apply_local(self, query: str, keyspace, extra: dict) -> None:
        """Execute the DDL against the local node WITHOUT re-entering
        the coordination path. Object ids the coordinator assigned ride
        in `extra` so every node agrees (mutations route by table id)."""
        if query.startswith(TOPOLOGY_PREFIX):
            apply_topology_to_ring(self.node.ring, extra)
            emit_topology_event(self.node, extra)
            return
        from ..cql.parser import parse
        from ..cql.execution import Executor
        stmt = parse(query)
        tid = extra.get("table_id")
        if tid is not None:
            name = type(stmt).__name__
            if name == "CreateTableStatement":
                stmt.options = dict(stmt.options or {})
                stmt.options["id"] = tid
            elif name == "CreateViewStatement":
                stmt.view_id = tid
        # NODE-LOCAL application: replayed entries must never re-enter
        # any distributed fan-out path
        Executor(self.node.engine).execute(stmt, keyspace=keyspace)

    def _extra_for(self, stmt, keyspace) -> dict:
        """After the coordinator applied the DDL: the ids peers must
        reuse."""
        if stmt is None:
            return {}
        name = type(stmt).__name__
        try:
            if name in ("CreateTableStatement", "CreateViewStatement"):
                ks = stmt.keyspace or keyspace
                return {"table_id":
                        str(self.node.schema.get_table(ks, stmt.name).id)}
        except KeyError:
            pass
        return {}

    # ----------------------------------------------------- coordination --

    def coordinate(self, query: str, keyspace, stmt, local_exec,
                   extra_override: dict | None = None):
        """Entry point from the CQL processor. Runs on a client/session
        thread (never the messaging dispatch thread), so it MAY block
        on responses. A CMS member commits through Paxos directly; any
        other node forwards to a live CMS member and applies the acked
        entry. NO local-commit fallback exists: if no CMS quorum is
        reachable the statement FAILS (MetadataUnavailable) — a
        minority partition must not fork the log."""
        from .cms import MetadataUnavailable
        members = self.cms.members()
        if self.node.endpoint in members:
            return self._coordinate_cms(query, keyspace, stmt,
                                        local_exec, extra_override)
        pre_epoch = self.epoch
        targets = [m for m in members if self.node.is_alive(m)]
        if not targets:
            raise MetadataUnavailable(
                f"no CMS member reachable "
                f"({[m.name for m in members]} all down)")
        ambiguous = False
        for des in targets:
            ack = self._forward(des, query, keyspace, extra_override)
            if ack is None:
                ambiguous = True
                continue     # this member unreachable: try the next
            epoch, extra = ack
            with self._lock:
                behind = epoch > self.epoch + 1
            if behind:
                # missed entries: the CMS member has them all (it just
                # committed `epoch`). Pull OUTSIDE the lock: the
                # response is processed on the dispatch thread, and
                # _on_pull_response needs this same lock — a pull
                # under the lock would deadlock-till-timeout and stall
                # every message on the node.
                self.pull_from_peers(timeout=self.FORWARD_TIMEOUT,
                                     prefer=des)
            with self._lock:
                if epoch == self.epoch + 1:
                    self._apply_entry(epoch, query, keyspace,
                                      extra or {}, coord=des.name)
                if self.epoch < epoch:
                    # committed cluster-wide, but this node could not
                    # catch up (peers unreachable mid-pull) — surface
                    # that rather than return success for a table this
                    # node does not have yet
                    raise SchemaForwardError(
                        f"DDL committed at epoch {epoch} but local "
                        f"catch-up failed (local epoch "
                        f"{self.epoch}); retry")
            from ..cql.execution import ResultSet
            return ResultSet([], [])   # DDL result shape
        if ambiguous:
            # a forward may have committed with only the ack lost.
            # Re-issuing a committed CREATE would fork its table id —
            # pull first; if our exact statement now appears, it
            # committed: done.
            self.pull_from_peers(timeout=self.FORWARD_TIMEOUT)
            if any(rec[1] == query
                   for rec in self.entries_after(pre_epoch)):
                from ..cql.execution import ResultSet
                return ResultSet([], [])
        raise MetadataUnavailable(
            f"no CMS member answered the DDL forward "
            f"({[m.name for m in members]})")

    def _coordinate_cms(self, query: str, keyspace, stmt, local_exec,
                        extra_override: dict | None):
        """CMS-member commit: execute locally (validation + object-id
        assignment), then decide the epoch via Paxos. The local
        execution happens FIRST so semantic errors (bad DDL) surface to
        the client without touching the log; the Paxos decision then
        makes the entry durable cluster-wide or fails the statement.
        A liveness quorum check fails fast BEFORE the local execution,
        so a minority-side statement normally leaves no local residue
        (a member dying mid-round can still strand a locally-applied
        statement — the client sees the error and retries)."""
        from .cms import MetadataUnavailable
        members = self.cms.members()
        need = len(members) // 2 + 1
        live = [m for m in members
                if m == self.node.endpoint or self.node.is_alive(m)]
        if len(live) < need:
            raise MetadataUnavailable(
                f"metadata commit needs {need}/{len(members)} CMS "
                f"members ({[m.name for m in members]}), "
                f"{len(live)} reachable")
        result = local_exec()
        extra = extra_override if extra_override is not None \
            else self._extra_for(stmt, keyspace)
        with self._lock:
            self._inflight_local.add(query)
        try:
            self.cms.commit_entry(query, keyspace, extra,
                                  already_applied=True)
        finally:
            with self._lock:
                self._inflight_local.discard(query)
        return result

    def _forward(self, des, query: str, keyspace, extra_override):
        """Send the DDL to the designated node; block for its ack.
        Returns (epoch, extra) on success, None if unreachable; raises
        SchemaForwardError if the designated node rejected the DDL."""
        done = threading.Event()
        box: dict = {}

        def on_rsp(msg):
            box["payload"] = msg.payload
            done.set()

        def on_fail(_msg_id):
            done.set()

        self.node.messaging.send_with_callback(
            Verb.SCHEMA_FORWARD, (query, keyspace, extra_override or {}),
            des, on_response=on_rsp, on_failure=on_fail,
            timeout=self.FORWARD_TIMEOUT)
        if not done.wait(self.FORWARD_TIMEOUT) or "payload" not in box:
            return None
        payload = box["payload"]
        if payload[0] == "err":
            raise SchemaForwardError(
                f"DDL rejected by designated coordinator "
                f"{des.name}: {payload[1]}")
        return int(payload[1]), payload[2] or {}

    # ---------------------------------------------------------- handlers --

    def _handle_forward(self, msg):
        """CMS-member side of a forwarded DDL. The Paxos commit BLOCKS
        on quorum responses, so the work runs on a worker thread and
        the ack is sent asynchronously (messaging.respond) — the
        dispatch thread must stay free to process the very promise/
        accept responses the commit is waiting for."""
        query, keyspace, fwd_extra = msg.payload

        def run():
            from ..cql.parser import parse
            try:
                if not self.cms.is_member():
                    raise SchemaForwardError(
                        f"{self.node.endpoint.name} is not a CMS "
                        f"member")
                extra = fwd_extra or {}
                with self._lock:
                    if query.startswith(TOPOLOGY_PREFIX):
                        self._apply_local(query, keyspace, extra)
                    else:
                        stmt = parse(query)
                        self._apply_local(query, keyspace, extra)
                        extra = extra or self._extra_for(stmt, keyspace)
                    self._inflight_local.add(query)
                try:
                    epoch = self.cms.commit_entry(
                        query, keyspace, extra, already_applied=True)
                finally:
                    with self._lock:
                        self._inflight_local.discard(query)
            except Exception as e:
                self.node.messaging.respond(
                    msg, Verb.SCHEMA_FORWARD, ("err", repr(e), None))
                return
            self.node.messaging.respond(
                msg, Verb.SCHEMA_FORWARD, ("ok", epoch, extra))

        threading.Thread(target=run, daemon=True,
                         name="schema-forward").start()
        return None

    def _handle_push(self, msg):
        epoch, query, keyspace, extra = msg.payload
        displaced = None
        with self._lock:
            if epoch == self.epoch + 1:
                self._apply_entry(epoch, query, keyspace, extra or {},
                                  coord=msg.sender.name)
                return None
            if epoch <= self.epoch:
                displaced = self._adopt_winner_locked(
                    epoch, query, keyspace, extra, msg.sender.name)
        if epoch > self.epoch + 1:
            # gap: pull the missing prefix from the sender. Async on
            # purpose — this handler runs on the single dispatch thread,
            # and the pull response can only be processed by that same
            # thread, so blocking here would deadlock the node.
            self.node.messaging.send_with_callback(
                Verb.SCHEMA_PULL,
                max(0, self.epoch - self.PULL_OVERLAP), msg.sender,
                on_response=self._on_pull_response,
                timeout=self.node.proxy.timeout)
        elif displaced is not None:
            self._recoordinate_async(displaced)
        return None

    def _adopt_winner_locked(self, epoch, query, keyspace, extra,
                             coord: str):
        """Same-epoch conflict resolution. With the CMS (cluster/cms.py)
        every epoch is Paxos-decided, so two nodes holding DIFFERENT
        entries at one epoch is impossible for CMS-committed logs —
        this path survives only as tolerance for logs predating the CMS
        and is LOUD when it fires (it would indicate log corruption or
        a mixed-version cluster). The entry whose coordinator has the
        HIGHER name wins deterministically; returns our displaced entry
        (for re-coordination) or None. Caller holds _lock."""
        mine = self._entry_at(epoch)
        if mine is None or mine[1] == query \
                or (coord or "") <= (mine[4] or ""):
            return None
        print(f"[schema-sync] {self.node.endpoint.name}: SAME-EPOCH "
              f"CONFLICT at {epoch} ({mine[1]!r} vs {query!r}) — "
              f"impossible for CMS-committed logs; adopting "
              f"deterministic winner. Investigate log integrity.",
              file=sys.stderr)
        self._apply_entry(epoch, query, keyspace, extra or {},
                          coord=coord)
        return mine

    def _recoordinate_async(self, displaced) -> None:
        """A displaced statement re-coordinates at a fresh epoch,
        keeping its assigned object ids. Runs on a separate thread:
        coordinate() blocks on responses, and callers here are on the
        dispatch thread."""
        _e, q, k, x, _c = displaced

        def run():
            try:
                self.coordinate(q, k, None, lambda: None,
                                extra_override=x)
            except Exception as e:
                # the statement's local side effects exist but it lost
                # its epoch and could not be re-committed — tell the
                # operator to re-issue it instead of losing it silently
                print(f"[schema-sync] {self.node.endpoint.name}: "
                      f"re-coordination of displaced DDL failed "
                      f"({q!r}): {e!r} — re-issue it manually",
                      file=sys.stderr)

        threading.Thread(target=run, daemon=True,
                         name="schema-recoordinate").start()

    def _handle_pull(self, msg):
        after = int(msg.payload)
        return Verb.SCHEMA_PUSH, ("entries", self.entries_after(after))

    def _on_pull_response(self, msg):
        tag, entries = msg.payload
        displaced_all = []
        with self._lock:
            for epoch, query, keyspace, extra, coord in entries:
                if epoch == self.epoch + 1:
                    self._apply_entry(epoch, query, keyspace,
                                      extra or {}, coord=coord)
                elif epoch <= self.epoch:
                    # overlap window: adopt a conflict winner this node
                    # missed (same deterministic rule as _handle_push) —
                    # and our displaced entry re-commits at a fresh
                    # epoch, exactly as if the push had arrived
                    d = self._adopt_winner_locked(epoch, query, keyspace,
                                                  extra, coord)
                    if d is not None:
                        displaced_all.append(d)
        for d in displaced_all:
            self._recoordinate_async(d)

    def _apply_entry(self, epoch: int, query: str, keyspace,
                     extra: dict, coord: str | None = None) -> None:
        """Apply + log a received entry. The coordinator NAME is
        recorded as received (never this node's own), because the
        same-epoch conflict rule compares against it — every node must
        store the same name or different nodes pick different winners."""
        try:
            self._apply_local(query, keyspace, extra)
        except Exception as e:
            # an entry that fails locally (e.g. already-applied effect)
            # still advances the epoch — convergence over strictness,
            # matching pre-TCM schema-merge behaviour. But NOT silently:
            # e.g. CREATE TRIGGER fails on a node missing the trigger
            # file, and the operator must learn this node diverged
            print(f"[schema-sync] {self.node.endpoint.name}: replicated "
                  f"DDL failed locally at epoch {epoch} ({query!r}): "
                  f"{e!r}", file=sys.stderr)
        self.epoch = max(self.epoch, epoch)
        self._append(epoch, query, keyspace, extra, coord=coord)

    def commit_topology(self, extra: dict) -> None:
        """Commit a topology transformation as an epoch-log entry —
        membership/placement rides the SAME ordered log as DDL (the
        reference's ClusterMetadata holds schema AND tokenMap/placements,
        all changed through one log). The entry text embeds the op so
        the same-epoch conflict rule dedups identical retries."""
        query = TOPOLOGY_PREFIX + json.dumps(extra, sort_keys=True)

        def local_apply():
            apply_topology_to_ring(self.node.ring, extra)
            emit_topology_event(self.node, extra)

        self.coordinate(query, None, None, local_apply,
                        extra_override=extra)

    def replay_all(self) -> None:
        """Re-apply every logged entry in epoch order (daemon restart).
        The ring is the log's materialization, so topology entries MUST
        replay; DDL that already exists fails benignly (warned)."""
        for e in sorted(self._entries):
            _epoch, query, keyspace, extra, _coord = self._entries[e]
            try:
                self._apply_local(query, keyspace, extra or {})
            except Exception as ex:
                print(f"[schema-sync] {self.node.endpoint.name}: replay "
                      f"of epoch {e} ({query[:60]!r}) failed: {ex!r}",
                      file=sys.stderr)

    def pull_from_peers(self, timeout: float = 5.0, prefer=None,
                        peers=None) -> bool:
        """Catch-up: ask a live peer (preferring `prefer`) for newer
        entries. Blocks on the response — callers must be off the
        dispatch thread (startup threads, session threads). `peers`
        overrides discovery — a FRESH node joining has an empty ring and
        only knows its configured seed addresses (tcm/Discovery role).
        Returns True if any peer answered (callers that REQUIRE the
        cluster's log — auto-join discovery — must treat False as
        fatal, not as 'I am the first node')."""
        if peers is None:
            peers = [ep for ep in self.node.ring.endpoints
                     if ep != self.node.endpoint and self.node.is_alive(ep)]
        else:
            peers = [ep for ep in peers if ep != self.node.endpoint]
        if prefer is not None and prefer in peers:
            peers.remove(prefer)
            peers.insert(0, prefer)
        for ep in peers:
            done = threading.Event()

            def on_rsp(msg):
                self._on_pull_response(msg)
                done.set()

            self.node.messaging.send_with_callback(
                Verb.SCHEMA_PULL,
                max(0, self.epoch - self.PULL_OVERLAP), ep,
                on_response=on_rsp, timeout=timeout)
            if done.wait(timeout):
                return True
        return False
