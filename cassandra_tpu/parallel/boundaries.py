"""Token-range boundary planning + mesh.* shard metrics — the jax-FREE
half of the mesh data plane (the ShardManager.computeBoundaries role).

These helpers are pure numpy and serve the host-engine mesh paths
(batched reads, range scans, native/numpy mesh compaction) that must
not pay mesh.py's module-level jax import. mesh.py re-exports
everything here so `parallel.mesh` imports keep working.

Boundary planning is count-weighted over DISTINCT cells: weighting by
raw input cells puts a hot, heavily-duplicated partition's shard at the
target input size but a fraction of the target OUTPUT size (the skewed
multichip sweep measured 21x kept-cell spread, 6.2k vs 130k).
Duplicates collapse in the merge, so the planner weights each token by
its distinct-identity count — from the batch itself
(`distinct_token_weights`) or, on the real compaction/read paths, from
the input sstables' partition directories (`boundaries_from_indexes`:
per-sstable per-partition cell counts, max-combined across inputs as
the distinct estimate).
"""
from __future__ import annotations

import numpy as np

_BIAS = np.uint64(1 << 63)


def plan_token_boundaries(uniq_tokens: np.ndarray, weights: np.ndarray,
                          n_shards: int) -> np.ndarray:
    """Greedy count-weighted quantile boundaries over DISTINCT tokens
    (ShardManager.computeBoundaries role). Returns the LAST token of
    each of the first n_shards-1 shards (uint64, biased token space);
    assignment is `searchsorted(bounds, tok, side='left')`, so equal
    tokens always stay together. Each boundary is chosen against the
    weight still unassigned, so a hot token that overshoots its shard's
    target makes the REMAINING shards re-balance around it instead of
    starving."""
    uniq = np.asarray(uniq_tokens, dtype=np.uint64)
    w = np.asarray(weights, dtype=np.int64)
    total = int(w.sum())
    cum = np.cumsum(w)
    bounds = np.empty(max(n_shards - 1, 0), dtype=np.uint64)
    taken = 0          # distinct tokens already assigned
    assigned = 0       # weight already assigned
    for s in range(n_shards - 1):
        ideal = (total - assigned) / (n_shards - s)
        target = assigned + ideal
        k = taken + int(np.searchsorted(cum[taken:], target, side="left"))
        if k >= len(cum):
            take = len(cum)
        else:
            below = (int(cum[k - 1]) if k > 0 else 0) - assigned
            above = int(cum[k]) - assigned
            # split by RELATIVE deviation from the ideal shard size: a
            # hot token right after a small remainder must be absorbed
            # (overshoot) rather than leave a starved sliver shard —
            # absolute distance picks the sliver when the hot token is
            # more than 2x the ideal

            def dev(sz):
                return max(sz / ideal, ideal / sz) if sz > 0 \
                    else float("inf")

            take = k + 1 if dev(above) <= dev(below) else k
        if taken < len(cum):
            take = max(take, taken + 1)   # a shard never goes empty
            # while distinct tokens remain
        take = min(take, len(cum))
        bounds[s] = uniq[take - 1] if take > 0 else uniq[0]
        assigned = int(cum[take - 1]) if take > 0 else 0
        taken = take
    return bounds


def batch_tokens_u64(cat) -> np.ndarray:
    """Biased uint64 tokens of every cell (lane0 << 32 | lane1)."""
    with np.errstate(over="ignore"):
        return (cat.lanes[:, 0].astype(np.uint64) << np.uint64(32)) \
            | cat.lanes[:, 1].astype(np.uint64)


def distinct_token_weights(cat) -> tuple[np.ndarray, np.ndarray]:
    """(distinct tokens asc, distinct-IDENTITY cell count per token).
    The planner's weight source for in-memory batches: duplicates of the
    same identity collapse in the merge, so balancing on raw cell counts
    leaves duplicate-heavy shards with a fraction of the target OUTPUT
    size. One np.unique over the full identity lanes counts survivors
    exactly (tombstone purging aside)."""
    K = cat.n_lanes
    tok = batch_tokens_u64(cat)
    keys = np.ascontiguousarray(cat.lanes.astype(">u4")).view(
        f"S{4 * K}").ravel()
    _, first = np.unique(keys, return_index=True)
    return np.unique(tok[first], return_counts=True)


def boundaries_from_indexes(readers, n_shards: int) -> np.ndarray | None:
    """Plan shard boundaries for a compaction round from the input
    sstables' partition directories — no data decode needed. Each
    reader's index yields (partition token, cell count) samples; counts
    are combined across inputs by MAX per token: within one sstable
    every identity is unique, so the max across inputs lower-bounds the
    distinct (post-merge) cell count and is exact when the runs fully
    overlap — the duplicate-heavy case the raw-sum weighting got wrong.
    Returns None when the inputs expose no partitions."""
    toks_all: list[np.ndarray] = []
    w_all: list[np.ndarray] = []
    for r in readers:
        n_part = getattr(r, "n_partitions", 0)
        if not n_part:
            continue
        tok = r.partition_tokens.astype(np.uint64) ^ _BIAS
        counts = np.diff(np.append(r._part_cell0, r.n_cells))
        toks_all.append(tok)
        w_all.append(counts.astype(np.int64))
    if not toks_all:
        return None
    tok = np.concatenate(toks_all)
    w = np.concatenate(w_all)
    order = np.argsort(tok, kind="stable")
    tok, w = tok[order], w[order]
    new = np.ones(len(tok), dtype=bool)
    new[1:] = tok[1:] != tok[:-1]
    grp = np.cumsum(new) - 1
    wmax = np.zeros(int(grp[-1]) + 1 if len(grp) else 0, dtype=np.int64)
    np.maximum.at(wmax, grp, w)
    return plan_token_boundaries(tok[new], wmax, n_shards)


def boundaries_to_ranges(bounds: np.ndarray,
                         n_shards: int) -> list[tuple[int, int]]:
    """Signed (lo, hi] token ranges per shard for SSTableReader
    .scan_tokens / Memtable.scan_window: shard s covers tokens in
    (bounds[s-1], bounds[s]], the first from int64 min, the last to
    int64 max. Biased-u64 order equals signed order after the bias
    XOR, so boundary membership is identical to searchsorted
    side='left' over the biased bounds."""
    signed = [int(np.int64(b ^ _BIAS)) for b in np.asarray(bounds,
                                                           np.uint64)]
    lo = -(1 << 63)
    out = []
    for s in range(n_shards):
        hi = signed[s] if s < len(signed) else (1 << 63) - 1
        out.append((lo, hi))
        lo = hi
    return out


def shard_imbalance(sizes) -> float:
    """max/mean shard-size factor (1.0 = perfectly balanced) — the skew
    health metric the multichip sweep reports per case. Unsplittable hot
    partitions lower-bound it at hot_cells / mean."""
    sizes = list(sizes)
    total = sum(sizes)
    if not sizes or total == 0:
        return 1.0
    return max(sizes) / (total / len(sizes))


# ------------------------------------------------------- mesh metrics --

_LAST_IMBALANCE = [1.0]
_GAUGES_REGISTERED = [False]


def record_shard_metrics(shard_cells, device_walls_s=None) -> None:
    """Fold one sharded round into the mesh.* metrics group: per-shard
    cell counts and device wall seconds as histograms, the round's
    max/mean imbalance as a gauge (Prometheus export picks all of them
    up through the global registry)."""
    from ..service.metrics import GLOBAL
    if not _GAUGES_REGISTERED[0]:
        GLOBAL.register_gauge("mesh.imbalance",
                              lambda: _LAST_IMBALANCE[0])
        _GAUGES_REGISTERED[0] = True
    sizes = [int(c) for c in shard_cells if c]
    GLOBAL.incr("mesh.rounds")
    GLOBAL.incr("mesh.shards", len(sizes))
    h = GLOBAL.hist("mesh.shard_cells")
    for c in sizes:
        h.update_us(c)
    if device_walls_s:
        hw = GLOBAL.hist("mesh.device_wall")
        for w in device_walls_s:
            if w > 0:
                hw.update_us(w * 1e6)
    _LAST_IMBALANCE[0] = shard_imbalance(sizes)
