"""Profile merge_sorted_device sub-phases (dev tool).

Defaults to production-like round sizes (4 x 64K cells = one pipelined
CompactionTask round). CTPU_PROF_CELLS overrides per-run cells — note
XLA's sort COMPILE time grows with N (~1 min at 1M cells cold), so big
sizes are slow on the first run; warm dispatch is what this measures."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

from cassandra_tpu.ops import merge as dmerge
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.tools import bulk
from cassandra_tpu.schema import make_table, TableParams
from cassandra_tpu.ops.codec import CompressionParams

N_RUNS = 4
CELLS = int(os.environ.get("CTPU_PROF_CELLS", 65_536))
VB = 64
NPART = 4096

table = make_table("bench", "stress", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "blob"},
                   params=TableParams(compression=CompressionParams("LZ4Compressor")))

rng = np.random.default_rng(2)
batches = []
for run in range(N_RUNS):
    pk = rng.integers(0, NPART, CELLS)
    ck = rng.integers(1, 10_000, CELLS)
    vals = rng.integers(0, 256, (CELLS, VB), dtype=np.uint8)
    ts = rng.integers(1, 1 << 40, CELLS).astype(np.int64)
    b = bulk.build_int_batch(table, pk, ck, vals, ts)
    batches.append(cb.merge_sorted([b]))


def one(tag):
    """Profile the ACTIVE device path (v3 fast planes when the round
    qualifies, else v2) through the shipped submit/collect API."""
    t = {}
    prof = {}
    t0 = time.perf_counter()
    cat = cb.CellBatch.concat(batches)
    n = len(cat)
    t["concat"] = time.perf_counter() - t0

    fast = dmerge._plane_pack_fast(cat, batches)
    if fast is not None:
        push_bytes = fast[0].nbytes
    else:
        planes, _cfg = dmerge._plane_pack_v2(cat, batches)
        push_bytes = sum(v.nbytes for v in planes.values()
                         if hasattr(v, "nbytes"))

    t0 = time.perf_counter()
    h = dmerge.submit_merge(batches, prof=prof)
    t["submit"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    merged = dmerge.collect_merge(h)
    t["collect"] = time.perf_counter() - t0

    print(tag, f"mode={h.mode} n={n} push_bytes={push_bytes} "
          f"({push_bytes/n:.1f} B/cell)",
          {k: round(v, 3) for k, v in t.items()},
          {k: round(v, 3) for k, v in prof.items()},
          f"kept={len(merged)}")


one("cold")
one("warm1")
one("warm2")
one("warm3")

# The same runs as the device-program registry sees them
# (service/profiling.py — system_views.device_programs): compile vs
# warm-dispatch vs execute split, live tracked shapes, recompile count
# past the budget, and XLA cost analysis where the backend reports it.
from cassandra_tpu.service import profiling  # noqa: E402

snap = profiling.GLOBAL.snapshot()
for name, k in sorted(snap["kernels"].items()):
    print(f"{name}: calls={k['calls']} compiles={k['compiles']} "
          f"shapes={k['shape_count']} evictions={k['shape_evictions']} "
          f"retraces={k['retraces']} compile={k['compile_s']:.3f}s "
          f"dispatch={k['dispatch_s']:.3f}s execute={k['execute_s']:.3f}s "
          f"flops={k['cost_flops']:.0f} bytes={k['cost_bytes']:.0f}")
for phase, secs in sorted(snap["phases"].items()):
    print(f"phase {phase}: {secs:.3f}s")
