"""Anti-entropy repair: merkle validation + range sync between replicas.

Reference counterpart: repair/RepairCoordinator.java:98 (session per
replica set, job per table), repair/Validator.java:61 (merkle build over
partition hashes via the compaction scanner), repair/SyncTask + the
streaming plan that moves mismatched ranges.

Flow: coordinator requests a VALIDATION from every replica (each hashes
its local partitions into a MerkleTree), diffs the trees pairwise, and for
every mismatched range pulls both sides' cells and pushes the merged truth
to whoever is missing data. Range data moves as columnar CellBatches — the
same wire shape streaming uses.
"""
from __future__ import annotations

import hashlib
import threading
import time

import numpy as np

from ..storage import cellbatch as cb
from ..utils.merkle import MerkleTree
from .coordinator import batch_to_mutation, cb_deserialize, cb_serialize
from .messaging import Verb
from .replication import ReplicationStrategy

_BIAS = 1 << 63


batch_tokens = cb.batch_tokens


def iter_partitions(batch: cb.CellBatch):
    """Yield (start, end, token) for every partition run of a sorted
    batch — the shared partition-boundary idiom."""
    n = len(batch)
    if n == 0:
        return
    toks = batch_tokens(batch)
    lane4 = batch.lanes[:, :4]
    part_new = np.ones(n, dtype=bool)
    part_new[1:] = (lane4[1:] != lane4[:-1]).any(axis=1)
    starts = np.flatnonzero(part_new)
    ends = np.append(starts[1:], n)
    for s, e in zip(starts, ends):
        yield int(s), int(e), int(toks[s])


filter_token_range = cb.filter_token_range


def build_validation_tree(table, batch: cb.CellBatch,
                          depth: int = 10) -> MerkleTree:
    """Validator role: hash every partition's reconciled cells into the
    tree (partition digest = md5 over lanes/ts/flags/payload of its
    cells)."""
    tree = MerkleTree(depth)
    n = len(batch)
    if n == 0:
        tree.seal()
        return tree
    for s, e, tok in iter_partitions(batch):
        h = hashlib.md5()
        h.update(batch.lanes[s:e].astype("<u4").tobytes())
        h.update(batch.ts[s:e].astype("<i8").tobytes())
        h.update(batch.flags[s:e].tobytes())
        h.update(batch.payload[batch.off[s]:batch.off[e]].tobytes())
        tree.add(tok, h.digest())
    tree.seal()
    return tree


class RepairSessionStore:
    """Durable repair-session records (repair/consistent/
    LocalSessions.java role): every coordinated session is journaled to
    repair_sessions.jsonl BEFORE it runs and finalized after, so a
    coordinator restart can report in-flight sessions (state
    IN_PROGRESS with no FINALIZED record) instead of forgetting them —
    the operator sees exactly which sessions died mid-flight
    (`nodetool repair_admin`)."""

    def __init__(self, directory: str):
        import os
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "repair_sessions.jsonl")
        self._lock = threading.Lock()

    def _append(self, rec: dict) -> None:
        import json
        import os
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())

    def begin(self, session_id: str, **info) -> None:
        self._append({"id": session_id, "state": "IN_PROGRESS", **info})

    def finish(self, session_id: str, state: str, **info) -> None:
        self._append({"id": session_id, "state": state, **info})

    def sessions(self) -> list[dict]:
        """Latest state per session id, oldest first — survives
        restarts (read back from the journal)."""
        import json
        import os
        out: dict[str, dict] = {}
        if not os.path.exists(self.path):
            return []
        with self._lock:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue   # torn tail after a crash
                    out[rec["id"]] = {**out.get(rec["id"], {}), **rec}
        return list(out.values())

    def in_flight(self) -> list[dict]:
        return [s for s in self.sessions()
                if s.get("state") == "IN_PROGRESS"]


class RepairService:
    """Per-node repair endpoint + coordinator entry point."""

    def __init__(self, node):
        self.node = node
        # completed session records (system_views.repairs / nodetool
        # repair history; repair/RepairRunnable session state role) —
        # bounded: old sessions age out at constant memory
        from collections import deque
        self.history: "deque[dict]" = deque(maxlen=256)
        self.sessions = RepairSessionStore(node.engine.data_dir)
        node.messaging.register_handler(Verb.REPAIR_VALIDATION_REQ,
                                        self._handle_validation)
        node.messaging.register_handler(Verb.REPAIR_SYNC_REQ,
                                        self._handle_sync)
        node.messaging.register_handler(Verb.REPAIR_ANTICOMPACT_REQ,
                                        self._handle_anticompact)

    # ------------------------------------------------------------ handlers

    def _local_batch(self, keyspace, table_name):
        return self.node.engine.store(keyspace, table_name).scan_all()

    def _validate_local(self, keyspace, table_name, depth, incremental):
        """Build the local validation tree. For an incremental session,
        FLUSH first and capture the unrepaired sstable generations that
        existed at validation time — only exactly those may be stamped
        repaired later (an sstable flushed mid-repair was never
        validated). Validation itself always covers the FULL data:
        comparing unrepaired-only views diverges once repaired status
        differs across replicas for the same cells; full trees converge
        and the anticompaction step still delivers the compaction-split
        benefit (the pre-consistent-repair reference model)."""
        table = self.node.schema.get_table(keyspace, table_name)
        gens = []
        if incremental:
            cfs = self.node.engine.store(keyspace, table_name)
            cfs.flush()
            gens = [s.desc.generation for s in cfs.live_sstables()
                    if not s.is_repaired]
        tree = build_validation_tree(
            table, self._local_batch(keyspace, table_name), depth)
        return tree, gens

    def _handle_validation(self, msg):
        keyspace, table_name, depth, *rest = msg.payload
        incremental = bool(rest[0]) if rest else False
        tree, gens = self._validate_local(keyspace, table_name, depth,
                                          incremental)
        if incremental:
            return Verb.REPAIR_VALIDATION_RSP, (tree.serialize(), gens)
        return Verb.REPAIR_VALIDATION_RSP, tree.serialize()

    def _handle_sync(self, msg):
        keyspace, table_name, lo, hi, *rest = msg.payload
        batch = filter_token_range(self._local_batch(keyspace, table_name),
                                   lo, hi)
        return Verb.RANGE_RSP, cb_serialize(batch)

    def _handle_anticompact(self, msg):
        keyspace, table_name, ranges, repaired_at, *rest = msg.payload
        gens = set(rest[0]) if rest and rest[0] is not None else None
        n = self.anticompact_local(keyspace, table_name,
                                   [tuple(r) for r in ranges],
                                   int(repaired_at), gens)
        return Verb.REPAIR_ANTICOMPACT_RSP, n

    # ------------------------------------------------------ anticompaction

    def anticompact_local(self, keyspace, table_name, ranges,
                          repaired_at: int, gens=None) -> int:
        """Split every UNREPAIRED sstable at the repaired-range boundary:
        in-range cells land in a new sstable stamped repaired_at,
        out-of-range cells in a new unrepaired one
        (db/compaction/CompactionManager.java:838 doAntiCompaction).
        Returns the number of sstables rewritten."""
        import numpy as np

        from ..storage.rewrite import rewrite_sstable

        cfs = self.node.engine.store(keyspace, table_name)
        MIN = -(1 << 63)
        done = 0
        with self.node.engine.compactions.cfs_lock(cfs):
            for sst in list(cfs.live_sstables()):
                if sst.is_repaired:
                    continue
                if gens is not None \
                        and sst.desc.generation not in gens:
                    continue  # flushed after validation: not validated
                segs = list(sst.scanner())
                if not segs:
                    continue
                cat = cb.CellBatch.concat(segs)
                cat.sorted = True
                in_mask = cb.token_range_mask(batch_tokens(cat), ranges)

                def fill_for(mask, cat=cat):
                    def fill(w):
                        idx = np.flatnonzero(mask)
                        if len(idx):
                            part = cat.apply_permutation(idx)
                            part.sorted = True
                            w.append(part)
                    return fill

                rewrite_sstable(
                    cfs, sst,
                    [(repaired_at, sst.level, fill_for(in_mask)),
                     (0, sst.level, fill_for(~in_mask))])
                done += 1
        return done

    # --------------------------------------------------------- coordinator

    def repair_table(self, keyspace: str, table_name: str,
                     depth: int = 10, timeout: float = 10.0,
                     incremental: bool = False,
                     preview: bool = False) -> dict:
        """Full-range repair of one table across its replica set
        (RepairJob). incremental=True validates/syncs only data that was
        never repaired, then ANTICOMPACTS on every replica: synced
        ranges split out of unrepaired sstables and are stamped
        repairedAt, so future repairs skip them and compaction never
        mixes across the boundary (repair/consistent/).

        preview=True runs VALIDATION ONLY (repair --preview,
        PreviewKind role): merkle trees are built and diffed but
        nothing streams and nothing is stamped — the stats report how
        much WOULD sync. Sessions journal durably through
        RepairSessionStore either way. Returns stats."""
        import uuid as _uuid
        session_id = str(_uuid.uuid4())
        self.sessions.begin(session_id, keyspace=keyspace,
                            table=table_name, incremental=incremental,
                            preview=preview,
                            coordinator=self.node.endpoint.name)
        try:
            stats = self._repair_table(keyspace, table_name, depth,
                                       timeout, incremental, preview)
        except Exception as e:
            self.sessions.finish(session_id, "FAILED", error=repr(e))
            raise
        self.sessions.finish(session_id, "COMPLETED", **{
            k: v for k, v in stats.items() if isinstance(v, (int, bool))})
        return stats

    def _repair_table(self, keyspace, table_name, depth, timeout,
                      incremental, preview) -> dict:
        node = self.node
        ks = node.schema.keyspaces[keyspace]
        strat = ReplicationStrategy.create(ks.params.replication)
        replicas = set()
        for ep in node.ring.endpoints:
            for tok in node.ring.endpoints[ep]:
                for r in strat.replicas(node.ring, tok):
                    replicas.add(r)
        replicas = sorted(replicas, key=lambda e: e.name)
        live = [r for r in replicas if node.is_alive(r)]
        if incremental and len(live) < len(replicas):
            # stamping data repaired while a replica is down would hide
            # its missing writes from future sessions (the reference
            # refuses incremental repair with dead endpoints)
            raise RuntimeError(
                f"incremental repair requires all replicas up "
                f"({len(live)}/{len(replicas)} live); run full repair")

        trees = {}
        val_gens: dict = {}
        table = node.schema.get_table(keyspace, table_name)
        ev = threading.Event()
        lock = threading.Lock()

        def want_all():
            return len(trees) >= len(live)

        for ep in live:
            if ep == node.endpoint:
                with lock:
                    tree, gens = self._validate_local(
                        keyspace, table_name, depth, incremental)
                    trees[ep] = tree
                    val_gens[ep] = gens
                    if want_all():
                        ev.set()
            else:
                def on_rsp(m, e=ep):
                    with lock:
                        if incremental:
                            tree_b, gens = m.payload
                            trees[e] = MerkleTree.deserialize(tree_b)
                            val_gens[e] = list(gens)
                        else:
                            trees[e] = MerkleTree.deserialize(m.payload)
                        if want_all():
                            ev.set()
                node.messaging.send_with_callback(
                    Verb.REPAIR_VALIDATION_REQ,
                    (keyspace, table_name, depth, incremental), ep,
                    on_response=on_rsp, timeout=timeout)
        ev.wait(timeout)
        if len(trees) < len(live):
            raise TimeoutError(
                f"validation responses {len(trees)}/{len(live)}")

        stats = {"replicas": len(live), "ranges_synced": 0,
                 "cells_streamed": 0}
        if preview:
            stats["preview"] = True
            stats["ranges_mismatched"] = 0
        # diff LEAF-WISE among that leaf range's replica set only — with
        # RF < cluster size, comparing full trees across non-replicas
        # would stream data to nodes that don't own it (placement
        # violation). A leaf crossing a vnode boundary uses the union of
        # the replica sets at its ends (conservative).
        sample = next(iter(trees.values()))
        eps = list(trees)
        n_leaves = sample.n_leaves
        synced: set[tuple] = set()
        for leaf in range(n_leaves):
            lo, hi = sample.leaf_range(leaf)
            owners = set(strat.replicas(node.ring, lo + 1)) | \
                set(strat.replicas(node.ring, hi))
            present = [e for e in eps if e in owners]
            for i in range(len(present)):
                for j in range(i + 1, len(present)):
                    a, b = present[i], present[j]
                    la = trees[a].leaves[leaf]
                    lb = trees[b].leaves[leaf]
                    if (la != lb).any():
                        key = (a, b, lo, hi)
                        if key in synced:
                            continue
                        synced.add(key)
                        if preview:
                            # validate-only: report, never stream
                            stats["ranges_mismatched"] += 1
                            continue
                        n = self._sync_range(keyspace, table_name, a, b,
                                             lo, hi, timeout)
                        stats["ranges_synced"] += 1
                        stats["cells_streamed"] += n

        if incremental and not preview:
            # the whole token space is now consistent across the replica
            # set: anticompact everywhere so repaired data crosses the
            # boundary and future incremental repairs skip it
            # module-level `time`: the simulator patches this module's
            # attribute, so repaired_at follows the virtual clock under
            # simulation (an aliased function-level import escaped the
            # patch — ctpulint clock-discipline)
            repaired_at = int(time.time() * 1000)
            ranges = [(-(1 << 63), (1 << 63) - 1)]
            done = {}
            aev = threading.Event()

            def want_all_ac():
                return len(done) >= len(live)

            for ep in live:
                if ep == node.endpoint:
                    with lock:
                        done[ep] = self.anticompact_local(
                            keyspace, table_name, ranges, repaired_at,
                            set(val_gens.get(ep, [])))
                        if want_all_ac():
                            aev.set()
                else:
                    def on_ac(m, e=ep):
                        with lock:
                            done[e] = m.payload
                            if want_all_ac():
                                aev.set()
                    node.messaging.send_with_callback(
                        Verb.REPAIR_ANTICOMPACT_REQ,
                        (keyspace, table_name, ranges, repaired_at,
                         val_gens.get(ep, [])), ep,
                        on_response=on_ac, timeout=timeout)
            if not aev.wait(timeout):
                raise TimeoutError(
                    f"anticompaction acks {len(done)}/{len(live)}")
            stats["anticompacted"] = sum(done.values())
            stats["repaired_at"] = repaired_at
        self.history.append({"keyspace": keyspace, "table": table_name,
                             "incremental": incremental,
                             "replicas": len(live), **stats})
        return stats

    def _fetch_range(self, ep, keyspace, table_name, lo, hi, timeout):
        node = self.node
        if ep == node.endpoint:
            return filter_token_range(
                self._local_batch(keyspace, table_name), lo, hi)
        # sessioned fetch (chunked + CRC + retransmit): a sync over a
        # flaky wire retries and converges instead of timing out whole
        batch = node.streams.fetch_batch(ep, keyspace, table_name,
                                         lo, hi, timeout)
        # deserialized batches lose the ck composite translator; range
        # tombstone reconciliation needs it back
        t = node.schema.get_table(keyspace, table_name)
        batch.ck_comp = t.clustering_comp
        return batch

    def _apply_batch(self, ep, table, merged: cb.CellBatch):
        """Push the merged truth for a range to a replica, one partition
        per mutation (SyncTask -> streaming role)."""
        node = self.node
        if len(merged) == 0:
            return
        for s, e, _tok in iter_partitions(merged):
            part = merged.slice_range(s, e)
            m = batch_to_mutation(table, part)
            if m is None:
                continue
            if ep == node.endpoint:
                node.engine.apply(m)
            else:
                node.messaging.send_one_way(Verb.MUTATION_REQ,
                                            m.serialize(), ep)

    def apply_batch_to_owners(self, keyspace: str, table,
                              batch: cb.CellBatch,
                              timeout: float = 10.0, ring=None) -> None:
        """Push every partition of a batch to that partition's replica
        set, acked (decommission / rebalance streaming must be durable
        before the sender departs). `ring` overrides the node's current
        ring — a token move pushes surrendered data to its POST-move
        owners before committing the flip."""
        node = self.node
        ks = node.schema.keyspaces[keyspace]
        strat = ReplicationStrategy.create(ks.params.replication)
        route_ring = ring if ring is not None else node.ring
        pending = threading.Semaphore(0)
        failures = []
        sent = 0
        for s, e, tok in iter_partitions(batch):
            part = batch.slice_range(s, e)
            m = batch_to_mutation(table, part)
            if m is None:
                continue
            for ep in strat.replicas(route_ring, tok):
                if ep == node.endpoint:
                    node.engine.apply(m)
                else:
                    sent += 1

                    def fail(_i, e=ep):
                        failures.append(e)
                        pending.release()

                    node.messaging.send_with_callback(
                        Verb.MUTATION_REQ, m.serialize(), ep,
                        on_response=lambda _m: pending.release(),
                        on_failure=fail, timeout=timeout)
        for _ in range(sent):
            if not pending.acquire(timeout=timeout):
                raise TimeoutError("stream push not acknowledged")
        if failures:
            raise RuntimeError(
                f"stream push failed to {len(failures)} replica(s): "
                f"{set(failures)} — aborting handoff")

    def _sync_range(self, keyspace, table_name, a, b, lo, hi,
                    timeout) -> int:
        table = self.node.schema.get_table(keyspace, table_name)
        batch_a = self._fetch_range(a, keyspace, table_name, lo, hi,
                                    timeout)
        batch_b = self._fetch_range(b, keyspace, table_name, lo, hi,
                                    timeout)
        merged = cb.merge_sorted([batch_a, batch_b])
        digest_a = _digest(batch_a)
        digest_b = _digest(batch_b)
        md = _digest(merged)
        if digest_a != md:
            self._apply_batch(a, table, merged)
        if digest_b != md:
            self._apply_batch(b, table, merged)
        return len(merged)


_digest = cb.content_digest
