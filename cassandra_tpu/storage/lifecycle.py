"""Crash-safe SSTable lifecycle transactions.

Reference counterpart: db/lifecycle/LogTransaction.java:101 and
LifecycleTransaction.java:81 — an on-disk txn log records the ADDed and
REMOVEd sstables of a compaction/flush swap; on restart an incomplete log
rolls back (delete new files), a committed one rolls forward (delete old
files). The log lives next to the sstables it governs.

Log format (text, one record per line):
    ADD <generation>
    REMOVE <generation>
    COMMIT
"""
from __future__ import annotations

import os
import uuid as uuid_mod

from .sstable.format import Component, Descriptor

_PREFIX = "txn-"
_SUFFIX = ".log"


def _delete_sstable_files(directory: str, generation: int) -> None:
    for fn in os.listdir(directory):
        parts = fn.split("-")
        # <version>-<gen>-<Component> or tmp-<version>-<gen>-<Component>
        if len(parts) >= 3:
            idx = 1 if parts[0] != "tmp" else 2
            try:
                gen = int(parts[idx])
            except (ValueError, IndexError):
                continue
            if gen == generation:
                try:
                    os.remove(os.path.join(directory, fn))
                except FileNotFoundError:
                    pass


class LifecycleTransaction:
    """Tracks one swap: stage ADDs/REMOVEs, then commit (atomic-enough:
    the COMMIT line is the decision point; file deletions follow)."""

    def __init__(self, directory: str):
        self.directory = directory
        self.id = uuid_mod.uuid4().hex[:12]
        self.path = os.path.join(directory, f"{_PREFIX}{self.id}{_SUFFIX}")
        self._adds: list[int] = []
        self._removes: list[int] = []
        self._file = open(self.path, "w")
        self._done = False

    def track_new(self, generation: int) -> None:
        self._adds.append(generation)
        self._file.write(f"ADD {generation}\n")
        self._file.flush()
        os.fsync(self._file.fileno())

    def track_obsolete(self, generation: int) -> None:
        self._removes.append(generation)
        self._file.write(f"REMOVE {generation}\n")
        self._file.flush()
        os.fsync(self._file.fileno())

    def commit(self) -> None:
        """Decision point: the fsynced COMMIT record makes the swap
        permanent; the deletions after it are best-effort (a crash there
        leaves the committed log for replay_directory to roll forward)."""
        self._file.write("COMMIT\n")
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        self._done = True   # from here on, abort() must NOT roll back
        try:
            for gen in self._removes:
                _delete_sstable_files(self.directory, gen)
            os.remove(self.path)
        except OSError:
            pass  # replay_directory finishes the roll-forward

    def abort(self) -> None:
        if self._done:
            return  # already committed: rolling back would lose data
        self._file.close()
        for gen in self._adds:
            _delete_sstable_files(self.directory, gen)
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass
        self._done = True


def replay_directory(directory: str) -> None:
    """Startup recovery: finish or roll back interrupted transactions
    (LogTransaction + LogAwareFileLister semantics). Also sweeps orphaned
    tmp- files from crashed writers."""
    if not os.path.isdir(directory):
        return
    for fn in list(os.listdir(directory)):
        if not (fn.startswith(_PREFIX) and fn.endswith(_SUFFIX)):
            continue
        path = os.path.join(directory, fn)
        with open(path) as f:
            lines = [l.strip() for l in f if l.strip()]
        committed = "COMMIT" in lines
        adds = [int(l.split()[1]) for l in lines if l.startswith("ADD")]
        removes = [int(l.split()[1]) for l in lines if l.startswith("REMOVE")]
        if committed:
            for gen in removes:     # roll forward
                _delete_sstable_files(directory, gen)
        else:
            for gen in adds:        # roll back
                _delete_sstable_files(directory, gen)
        os.remove(path)
    for fn in list(os.listdir(directory)):
        if fn.startswith("tmp-") or fn.endswith(".stream"):
            # .stream: a stream receiver's staged component rename that
            # never happened (crash mid-landing)
            try:
                os.remove(os.path.join(directory, fn))
            except FileNotFoundError:
                pass
    # stream landings commit by writing the TOC last: a generation with
    # components but no TOC is a crashed landing — invisible to
    # Descriptor.discover, and swept here so it can't leak disk forever
    toc_gens, part_gens = set(), set()
    for fn in os.listdir(directory):
        parts = fn.split("-", 2)
        if len(parts) != 3 or not parts[1].isdigit() \
                or not parts[0].isalpha():
            continue
        gen = int(parts[1])
        part_gens.add(gen)
        if parts[2] == Component.TOC:
            toc_gens.add(gen)
    for gen in part_gens - toc_gens:
        _delete_sstable_files(directory, gen)
