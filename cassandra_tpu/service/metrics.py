"""Metrics registry v2: counters, gauges and DECAYING latency histograms.

Reference counterpart: metrics/CassandraMetricsRegistry.java (Dropwizard)
with TableMetrics / ClientRequestMetrics / CompactionMetrics groups and
DecayingEstimatedHistogramReservoir latency tracking.

What changed from v1 (the immortal histogram): percentiles now come from
a sliding two-chunk window (default 60 s per chunk), so a latency spike
an hour ago no longer pollutes p99 forever — the reference solves the
same problem with a forward-decaying reservoir; a chunked window is the
equivalent that stays exact, lock-cheap and unit-testable with an
injected clock. Lifetime count / mean are still tracked (they are
monotonic by nature); percentiles and max decay.

Naming scheme (enforced by scripts/check_metric_names.py and documented
in docs/observability.md): dot-separated lowercase components,
`group.sub…name`, at least two components, each matching
[a-z0-9_]+ — e.g. `cql.request`, `compaction.tasks_completed`,
`table.<ks>.<table>.writes`, `verb.read_req.received`.

Export surfaces: snapshot() (flat dict — the system_views.metrics
vtable), group() (prefixed facade for per-table / per-verb metrics),
register_gauge() (callables polled at snapshot time), and
prometheus_text() (Prometheus exposition format, served by
`nodetool exportmetrics` and embedded in bench.py output).
"""
from __future__ import annotations

import math
import re
import threading
import time


class LatencyHistogram:
    """Log-scale bucket histogram of microsecond latencies with a
    sliding-window decay: updates land in the CURRENT chunk; reads
    aggregate the current + previous chunk and rotate expired ones, so
    percentiles/max reflect roughly the last `window_s`..2×`window_s`
    seconds. Lifetime count/total are immortal (monotonic)."""

    N_BUCKETS = 64

    def __init__(self, window_s: float = 60.0, clock=time.monotonic):
        self.window_s = float(window_s)
        self.clock = clock
        self.count = 0          # lifetime
        self.total_us = 0       # lifetime
        self._lock = threading.Lock()
        self._chunks: list = []  # [chunk_start, buckets, max_us], newest last
        self._new_chunk()

    def _new_chunk(self) -> None:
        self._chunks.append([self.clock(), [0] * self.N_BUCKETS, 0.0])

    def _rotate_locked(self) -> None:
        now = self.clock()
        if now - self._chunks[-1][0] >= self.window_s:
            self._new_chunk()
        # keep current + previous only
        while len(self._chunks) > 2 or (
                len(self._chunks) == 2
                and now - self._chunks[0][0] >= 2 * self.window_s):
            if len(self._chunks) == 1:
                break
            self._chunks.pop(0)

    def update_us(self, us: float) -> None:
        b = min(int(math.log2(max(us, 1))), self.N_BUCKETS - 1)
        with self._lock:
            self._rotate_locked()
            self._chunks[-1][1][b] += 1
            if us > self._chunks[-1][2]:
                self._chunks[-1][2] = us
            self.count += 1
            self.total_us += us

    # ---- windowed reads (all take the lock: the count/mean/bucket race
    # of v1 is gone — see MetricsRegistry.snapshot)

    def _window_buckets_locked(self):
        self._rotate_locked()
        agg = [0] * self.N_BUCKETS
        for _t0, buckets, _mx in self._chunks:
            for i, c in enumerate(buckets):
                agg[i] += c
        return agg

    def _percentile_of(self, buckets, total, p: float) -> float:
        if not total:
            return 0.0
        target = total * p
        acc = 0
        for b, c in enumerate(buckets):
            acc += c
            if acc >= target:
                return float(2 ** b)
        return float(2 ** (self.N_BUCKETS - 1))

    def percentile(self, p: float) -> float:
        with self._lock:
            buckets = self._window_buckets_locked()
            return self._percentile_of(buckets, sum(buckets), p)

    @property
    def max_us(self) -> float:
        with self._lock:
            self._rotate_locked()
            return max((c[2] for c in self._chunks), default=0.0)

    @property
    def mean_us(self) -> float:
        with self._lock:
            return self.total_us / self.count if self.count else 0.0

    def summary(self) -> dict:
        """One consistent read of count/mean/percentiles/max under a
        single lock acquisition (the snapshot surface)."""
        with self._lock:
            buckets = self._window_buckets_locked()
            total = sum(buckets)
            return {
                "count": self.count,
                "total_us": self.total_us,
                "mean_us": round(self.total_us / self.count, 1)
                if self.count else 0.0,
                "p50_us": self._percentile_of(buckets, total, 0.50),
                "p95_us": self._percentile_of(buckets, total, 0.95),
                "p99_us": self._percentile_of(buckets, total, 0.99),
                "max_us": max((c[2] for c in self._chunks), default=0.0),
            }


class Timer:
    def __init__(self, hist: LatencyHistogram):
        self.hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.update_us((time.perf_counter() - self._t0) * 1e6)


class MetricGroup:
    """Prefix facade: metrics.group('table.ks.t').incr('writes') lands
    on 'table.ks.t.writes' (the TableMetrics / per-verb group role)."""

    def __init__(self, registry: "MetricsRegistry", prefix: str):
        self.registry = registry
        self.prefix = prefix

    def _n(self, name: str) -> str:
        return f"{self.prefix}.{name}"

    def incr(self, name: str, n: int = 1) -> None:
        self.registry.incr(self._n(name), n)

    def counter(self, name: str) -> int:
        return self.registry.counter(self._n(name))

    def hist(self, name: str) -> LatencyHistogram:
        return self.registry.hist(self._n(name))

    def timer(self, name: str) -> Timer:
        return self.registry.timer(self._n(name))


class MetricsRegistry:
    """Grouped counters + gauges + decaying histograms:
    metrics.group('table.ks.t').incr(..)"""

    def __init__(self, window_s: float = 60.0):
        self.window_s = window_s
        self._counters: dict[str, int] = {}
        self._hists: dict[str, LatencyHistogram] = {}
        self._gauges: dict[str, callable] = {}
        self._lock = threading.Lock()

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        return self._counters.get(name, 0)

    def hist(self, name: str) -> LatencyHistogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram(self.window_s)
            return h

    def timer(self, name: str) -> Timer:
        return Timer(self.hist(name))

    def group(self, prefix: str) -> MetricGroup:
        return MetricGroup(self, prefix)

    def register_gauge(self, name: str, fn) -> None:
        """fn() -> number, polled at snapshot/export time (Dropwizard
        Gauge role). Re-registering a name replaces the callable."""
        with self._lock:
            self._gauges[name] = fn

    def unregister_gauge(self, name: str) -> None:
        with self._lock:
            self._gauges.pop(name, None)

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            hists = list(self._hists.items())
            gauges = list(self._gauges.items())
        # histogram reads happen OUTSIDE the registry lock (each hist
        # serializes its own summary): keeps snapshot cheap under
        # concurrent updates while reading count/mean/buckets
        # consistently. Live engine-scoped gauges remain engine-scoped
        # by design — see CompactionManager.gauges() / the
        # system_views.metrics vtable — so in-process multi-node
        # deployments never cross-report.
        for name, h in hists:
            s = h.summary()
            out[f"{name}.count"] = s["count"]
            out[f"{name}.mean_us"] = s["mean_us"]
            out[f"{name}.p50_us"] = s["p50_us"]
            out[f"{name}.p95_us"] = s["p95_us"]
            out[f"{name}.p99_us"] = s["p99_us"]
            out[f"{name}.max_us"] = s["max_us"]
        for name, fn in gauges:
            try:
                out[name] = fn()
            except Exception:
                pass   # a dead gauge must not break the whole snapshot
        return out


def _prom_name(name: str) -> str:
    return "ctpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _escape_label(value: str) -> str:
    """Prometheus exposition label-value escaping (backslash, quote,
    newline — in that order, so the escapes themselves survive). A
    hostile metric/label value must never be able to inject extra
    labels or lines into the scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(registry: "MetricsRegistry" = None,
                    extra_gauges: dict | None = None) -> str:
    """Render the registry in Prometheus exposition format: counters as
    `counter`, gauges as `gauge`, histograms as `summary` (quantile
    labels from the decayed window + lifetime _count/_sum). Served by
    `nodetool exportmetrics` and embedded in bench output."""
    reg = registry if registry is not None else GLOBAL
    with reg._lock:
        counters = sorted(reg._counters.items())
        hists = sorted(reg._hists.items())
        gauges = sorted(reg._gauges.items())
    lines = []
    for name, v in counters:
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {v}")
    for name, h in hists:
        s = h.summary()   # count/sum from ONE lock acquisition: a scrape
        # racing updates must never emit a _sum that includes samples
        # its _count does not
        pn = _prom_name(name) + "_us"
        lines.append(f"# TYPE {pn} summary")
        for q, key in (("0.5", "p50_us"), ("0.95", "p95_us"),
                       ("0.99", "p99_us")):
            lines.append(
                f'{pn}{{quantile="{_escape_label(q)}"}} {s[key]}')
        lines.append(f"{pn}_count {s['count']}")
        lines.append(f"{pn}_sum {float(s['total_us'])}")
    for name, fn in gauges:
        try:
            v = fn()
        except Exception:
            continue
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    if extra_gauges:
        for name, v in sorted(extra_gauges.items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {v}")
    return "\n".join(lines) + "\n"


GLOBAL = MetricsRegistry()
