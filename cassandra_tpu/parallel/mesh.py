"""Multi-chip compaction: token-range sharding over a jax.sharding.Mesh.

Design (SURVEY.md section 5.7): the reference parallelises compaction
within a node via UCS's ShardManager (db/compaction/ShardManager.java:33 —
token-range shards compacted independently) and across the cluster by
ownership. The TPU formulation is the same idea on a device mesh: the
token ring is split into one contiguous range per device, each device
runs the merge/reconcile kernel on its shard (shard_map; no cross-device
traffic for the merge itself — shards are disjoint), and per-shard stats
are combined with psum over ICI.

The same step doubles as the driver's multichip dry run: it is the full
"training step" of this framework — one round of the LSM data plane.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.merge import merge_reconcile_kernel
from ..storage.cellbatch import (DEATH_FLAGS, FLAG_COMPLEX_DEL,
                                 FLAG_EXPIRING, CellBatch)


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"mesh needs {n_devices} devices, backend "
                f"{jax.default_backend()!r} has {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("shard",))


# ------------------------------------------------------------- host split --

def shard_batch(cat: CellBatch, n_shards: int, gc_before: int = 0,
                now: int = 0) -> tuple[dict, np.ndarray, np.ndarray]:
    """Split a concatenated (unsorted) batch into n token-range shards of
    equal padded size and build the [S, N] operand arrays for
    sharded_merge_step. Returns (operands, shard_of_cell, position_in_shard)
    so the host can map kernel outputs back to cells.

    Shard boundaries are count-balanced quantiles of the token distribution
    (ShardManager.computeBoundaries role), weighted by per-token cell
    counts: boundaries land between DISTINCT tokens and each one is chosen
    greedily against the cells still unassigned, so a hot partition that
    overshoots its shard's target makes the remaining shards re-balance
    around it instead of starving (the naive positional quantile gave
    130k-vs-6.2k shards on the skewed multichip sweep)."""
    n = len(cat)
    with np.errstate(over="ignore"):
        tok = (cat.lanes[:, 0].astype(np.uint64) << np.uint64(32)) \
            | cat.lanes[:, 1].astype(np.uint64)
    uniq, counts = np.unique(tok, return_counts=True)
    cum = np.cumsum(counts)
    bounds = np.empty(n_shards - 1, dtype=np.uint64)
    taken = 0          # distinct tokens already assigned
    assigned = 0       # cells already assigned
    for s in range(n_shards - 1):
        ideal = (n - assigned) / (n_shards - s)
        target = assigned + ideal
        k = taken + int(np.searchsorted(cum[taken:], target, side="left"))
        if k >= len(cum):
            take = len(cum)
        else:
            below = (int(cum[k - 1]) if k > 0 else 0) - assigned
            above = int(cum[k]) - assigned
            # split by RELATIVE deviation from the ideal shard size: a
            # hot token right after a small remainder must be absorbed
            # (overshoot) rather than leave a starved sliver shard —
            # absolute distance picks the sliver when the hot token is
            # more than 2x the ideal

            def dev(sz):
                return max(sz / ideal, ideal / sz) if sz > 0 \
                    else float("inf")

            take = k + 1 if dev(above) <= dev(below) else k
        if taken < len(cum):
            take = max(take, taken + 1)   # a shard never goes empty
            # while distinct tokens remain
        take = min(take, len(cum))
        # bounds[s] = LAST token of shard s; equal tokens stay together
        # on the left side (side='left' assignment below)
        bounds[s] = uniq[take - 1] if take > 0 else uniq[0]
        assigned = int(cum[take - 1]) if take > 0 else 0
        taken = take
    shard_of = np.searchsorted(bounds, tok, side="left").astype(np.int32)

    counts = np.bincount(shard_of, minlength=n_shards)
    N = max(1024, int(1 << int(np.ceil(np.log2(max(counts.max(), 1))))))

    K = cat.n_lanes
    S = n_shards
    lanes = np.full((S, N, K), 0xFFFFFFFF, dtype=np.uint32)
    valid = np.ones((S, N), dtype=np.uint32)
    ts_h = np.zeros((S, N), dtype=np.uint32)
    ts_l = np.zeros((S, N), dtype=np.uint32)
    death = np.zeros((S, N), dtype=np.uint32)
    cdel = np.zeros((S, N), dtype=np.uint32)
    ldt = np.zeros((S, N), dtype=np.int32)
    expiring = np.zeros((S, N), dtype=np.uint32)
    purge = np.full((S, N), 0xFFFFFFFF, dtype=np.uint32)

    with np.errstate(over="ignore"):
        uts = cat.ts.astype(np.uint64) ^ np.uint64(1 << 63)
    pos_in_shard = np.zeros(n, dtype=np.int64)
    shard_members: list[np.ndarray] = []
    for s in range(S):
        idx = np.flatnonzero(shard_of == s)
        shard_members.append(idx)
        c = len(idx)
        pos_in_shard[idx] = np.arange(c)
        lanes[s, :c] = cat.lanes[idx]
        valid[s, :c] = 0
        ts_h[s, :c] = (uts[idx] >> np.uint64(32)).astype(np.uint32)
        ts_l[s, :c] = (uts[idx] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        death[s, :c] = (cat.flags[idx] & DEATH_FLAGS) != 0
        cdel[s, :c] = (cat.flags[idx] & FLAG_COMPLEX_DEL) != 0
        ldt[s, :c] = cat.ldt[idx]
        expiring[s, :c] = (cat.flags[idx] & FLAG_EXPIRING) != 0

    operands = {
        "lanes": lanes, "valid": valid, "ts_h": ts_h, "ts_l": ts_l,
        "death": death, "cdel": cdel, "ldt": ldt,
        "expiring": expiring, "purge_h": purge, "purge_l": purge.copy(),
        "gc_before": np.int32(gc_before), "now": np.int32(now),
    }
    return operands, shard_of, pos_in_shard, shard_members


def shard_imbalance(sizes) -> float:
    """max/mean shard-size factor (1.0 = perfectly balanced) — the skew
    health metric the multichip sweep reports per case. Unsplittable hot
    partitions lower-bound it at hot_cells / mean."""
    sizes = list(sizes)
    total = sum(sizes)
    if not sizes or total == 0:
        return 1.0
    return max(sizes) / (total / len(sizes))


# ----------------------------------------------------------- device step --

_step_cache: dict = {}


def sharded_merge_step(mesh: Mesh):
    """Build (or fetch the cached) jitted sharded compaction step for a
    mesh. Input operands carry a leading shard axis partitioned over the
    mesh; each device sorts and reconciles its token range locally, then
    global stats (cells kept, tombstones purged) are psum'd across the
    mesh. Cached per device tuple so repeated rounds reuse one jit
    program (compiles are expensive on this box)."""
    key = tuple(id(d) for d in mesh.devices.flat)
    cached = _step_cache.get(key)
    if cached is not None:
        return cached

    def per_shard(operands):
        # operands arrive with a leading axis of local size 1
        local = {k: (v[0] if getattr(v, "ndim", 0) > 0 else v)
                 for k, v in operands.items()}
        perm, packed = merge_reconcile_kernel(local)
        kept = jnp.sum((packed & 1).astype(jnp.int32))
        dropped = jnp.sum((local["valid"] == 0).astype(jnp.int32)) - kept
        stats = jnp.stack([kept, dropped])
        stats = jax.lax.psum(stats, axis_name="shard")
        return perm[None], packed[None], stats

    arr_spec = P("shard")
    scalar_spec = P()
    in_specs = ({k: (arr_spec if k not in ("gc_before", "now")
                     else scalar_spec)
                 for k in ("lanes", "valid", "ts_h", "ts_l", "death",
                           "cdel", "ldt", "expiring", "purge_h", "purge_l",
                           "gc_before", "now")},)
    out_specs = (arr_spec, arr_spec, P())

    step = jax.jit(jax.shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))
    _step_cache[key] = step
    return step


def _run_sharded(cat: CellBatch, mesh: Mesh, gc_before: int, now: int):
    """split -> device step -> host tie-break. Returns the full per-shard
    state (keep/perm/masks in shard-padded [S, N] layout, member index
    lists, psum'd stats)."""
    from ..ops.merge import host_tiebreak, unpack_masks

    n_shards = mesh.devices.size
    operands, shard_of, pos, members = shard_batch(cat, n_shards,
                                                   gc_before, now)
    step = sharded_merge_step(mesh)
    jop = {k: jnp.asarray(v) for k, v in operands.items()}
    import time as _time

    from ..service.profiling import GLOBAL as _kprof
    t0 = _time.perf_counter()
    perm, packed, stats = step(jop)
    _kprof.record_dispatch(
        "merge.sharded_step",
        (mesh.devices.size, tuple(jop["lanes"].shape)),
        _time.perf_counter() - t0)
    t0 = _time.perf_counter()
    perm = np.asarray(perm)
    _kprof.record_execute("merge.sharded_step",
                          _time.perf_counter() - t0)
    keep, amb, expired, shadowed = unpack_masks(np.asarray(packed))
    # equal-(identity, ts) winners need the exact death/value rules — per
    # shard, map sorted positions back into cat and resolve on host.
    # The device stats (psum over the mesh) are adjusted by the (rare)
    # tie-break keep-count delta instead of being recomputed.
    delta = 0
    for s in range(n_shards):
        c = len(members[s])
        if c == 0 or not amb[s, :c].any():
            continue
        before = int(keep[s, :c].sum())
        perm_real = members[s][perm[s, :c]]
        host_tiebreak(cat, perm_real, keep[s, :c], amb[s, :c],
                      shadowed[s, :c], expired[s, :c], gc_before, None)
        delta += int(keep[s, :c].sum()) - before
    stats = np.asarray(stats) + np.array([delta, -delta])
    return (keep, perm, expired, shadowed, stats, shard_of, pos, members)


def run_sharded_merge(cat: CellBatch, mesh: Mesh, gc_before: int = 0,
                      now: int = 0):
    """Host orchestration: split -> device step -> host tie-break ->
    per-shard outputs. Returns (keep [S,N] numpy, perm [S,N],
    stats (kept, dropped), shard_of, pos_in_shard)."""
    keep, perm, _, _, stats, shard_of, pos, _ = _run_sharded(
        cat, mesh, gc_before, now)
    return keep, perm, stats, shard_of, pos


def materialize_sharded_merge(cat: CellBatch, mesh: Mesh,
                              gc_before: int = 0,
                              now: int = 0) -> list[CellBatch]:
    """Per-shard merged CellBatches, token-ordered: shard s holds exactly
    the cells whose token falls in its range, reconciled, sorted. The
    concatenation equals the single-device merge output bit-for-bit, and
    each element can feed its own SSTableWriter — the ShardManager model
    (db/compaction/ShardManager.java:33: disjoint token shards feed
    independent writers)."""
    from ..ops.merge import finalize_merged

    keep, perm, expired, shadowed, _, _, _, members = _run_sharded(
        cat, mesh, gc_before, now)
    out: list[CellBatch] = []
    for s in range(len(members)):
        c = len(members[s])
        if c == 0:
            out.append(CellBatch.empty(cat.n_lanes))
            continue
        perm_real = members[s][perm[s, :c]]
        out.append(finalize_merged(cat, perm_real, keep[s, :c],
                                   expired[s, :c], shadowed[s, :c]))
    return out


def sharded_compact_to_sstables(batches: list[CellBatch], table, mesh,
                                directory: str, generation_base: int = 0,
                                gc_before: int = 0, now: int = 0,
                                shards: list[CellBatch] | None = None):
    """One compaction round over the mesh, landing one sstable per shard:
    merge the input CellBatches sharded across devices, then write each
    shard's reconciled output through a real SSTableWriter. Pass
    precomputed `shards` (from materialize_sharded_merge) to skip the
    merge. Returns the list of (Descriptor, stats) for non-empty shards."""
    from ..storage.sstable.format import Descriptor
    from ..storage.sstable.writer import SSTableWriter

    import os

    if shards is None:
        cat = CellBatch.concat(batches)
        shards = materialize_sharded_merge(cat, mesh, gc_before, now)
    results = []
    try:
        for s, shard in enumerate(shards):
            if len(shard) == 0:
                continue
            desc = Descriptor(directory, generation_base + s)
            w = SSTableWriter(desc, table)
            try:
                w.append(shard)
                stats = w.finish()
            except BaseException:
                w.abort()
                raise
            results.append((desc, stats))
    except BaseException:
        # all-or-nothing round (LifecycleTransaction semantics): a failed
        # shard write must not leave earlier shards' sstables behind as a
        # partial compaction output
        for desc, _stats in results:
            for p in desc.all_paths():
                if os.path.exists(p):
                    os.remove(p)
        raise
    return results
