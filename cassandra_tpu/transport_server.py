"""Back-compat shim: the native-protocol endpoint moved to
`cassandra_tpu/transport/`.

  transport/frame.py      the v4/v5 wire codec (envelopes, v5 CRC
                          segment framing, body primitives, result
                          encoding) — byte-compatible with the codec
                          that lived here.
  transport/server.py     the selector-based event-loop CQLServer that
                          replaced the thread-per-connection server.
  transport/admission.py  permit gate, overload signals, per-client
                          rate limiting.

Everything importable from this module before the move still is; new
code should import from `cassandra_tpu.transport` directly.
"""
from .transport.frame import (  # noqa: F401
    ERR_BAD_CREDENTIALS, ERR_INVALID, ERR_OVERLOADED, ERR_PROTOCOL,
    ERR_SERVER, ERR_UNPREPARED, EVENT_TYPES, MAX_SEGMENT_PAYLOAD,
    OP_AUTH_RESPONSE, OP_AUTH_SUCCESS, OP_AUTHENTICATE, OP_ERROR,
    OP_EVENT, OP_EXECUTE, OP_OPTIONS, OP_PREPARE, OP_QUERY, OP_READY,
    OP_REGISTER, OP_RESULT, OP_STARTUP, OP_SUPPORTED, RESULT_PREPARED,
    RESULT_ROWS, RESULT_SCHEMA_CHANGE, RESULT_SET_KEYSPACE, RESULT_VOID,
    SUPPORTED_VERSIONS, VERSION_REQ, VERSION_RSP, WireValue, _bytes,
    _crc24, _crc32_v5, _encode_rows, _inet, _infer_type, _long_string,
    _read_bytes, _read_long_string, _read_string, _read_string_map,
    _string, decode_segment_header, encode_envelope, encode_segment,
    error_body, frame_envelope, unprepared_body)
from .transport.server import CQLServer, Connection, _cert_identity  # noqa: F401

# the old per-connection state class was called _Conn
_Conn = Connection
