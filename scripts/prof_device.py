"""Profile merge_sorted_device sub-phases at bench shape (dev tool)."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

from cassandra_tpu.ops import merge as dmerge
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.tools import bulk
from cassandra_tpu.schema import make_table, TableParams
from cassandra_tpu.ops.codec import CompressionParams

N_RUNS = 4
CELLS = 262_144
VB = 64
NPART = 4096

table = make_table("bench", "stress", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "blob"},
                   params=TableParams(compression=CompressionParams("LZ4Compressor")))

rng = np.random.default_rng(2)
batches = []
for run in range(N_RUNS):
    pk = rng.integers(0, NPART, CELLS)
    ck = rng.integers(1, 10_000, CELLS)
    vals = rng.integers(0, 256, (CELLS, VB), dtype=np.uint8)
    ts = rng.integers(1, 1 << 40, CELLS).astype(np.int64)
    b = bulk.build_int_batch(table, pk, ck, vals, ts)
    batches.append(cb.merge_sorted([b]))


def one(tag):
    t = {}
    t0 = time.perf_counter()
    cat = cb.CellBatch.concat(batches)
    n = len(cat)
    t["concat"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    planes, cfg = dmerge._plane_pack_v2(cat, batches)
    t["pack"] = time.perf_counter() - t0
    push_bytes = sum(v.nbytes for v in planes.values() if hasattr(v, "nbytes"))

    t0 = time.perf_counter()
    planes_d = {k: jax.device_put(v) for k, v in planes.items()}
    jax.block_until_ready(list(planes_d.values()))
    t["push"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = dmerge._plane_program(planes_d, cfg)
    out.block_until_ready()
    t["program"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    combined = np.asarray(out)
    t["pull"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    perm = (combined & 0x00FFFFFF).astype(np.int64)[:n]
    bits = (combined >> 24).astype(np.uint8)[:n]
    keep, ambiguous, _, shadowed = dmerge.unpack_masks(bits)
    flags_s = cat.flags[perm]
    ldt_s = cat.ldt[perm]
    ts_s = cat.ts[perm]
    expired = ((flags_s & cb.FLAG_EXPIRING) != 0) & (ldt_s <= 0)
    t["post"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    merged = dmerge.finalize_merged(cat, perm, keep, expired, shadowed)
    t["finalize"] = time.perf_counter() - t0

    print(tag, f"n={n} push_bytes={push_bytes} ({push_bytes/n:.1f} B/cell)",
          {k: round(v, 3) for k, v in t.items()}, f"kept={len(merged)}")


one("cold")
one("warm1")
one("warm2")
one("warm3")
