"""Device merge/reconcile kernel — the TPU form of the compaction pipeline.

The reference merges k sorted SSTable scanners through a binary heap one row
at a time (utils/MergeIterator.java:23, CompactionIterator.java:90). The
TPU formulation: concatenate the runs' identity lanes, run ONE stable
variadic sort (jax.lax.sort), then compute winners / deletion shadowing /
purge as masks with segmented scans (lax.associative_scan). Everything is
uint32 lanes — 64-bit quantities travel as (hi, lo) pairs and compare
pairwise — so the kernel maps directly onto TPU vector units with no 64-bit
emulation.

Outputs are a permutation + keep mask; the host applies them to the
variable-length payload with numpy gathers (storage/cellbatch.py). Value
tie-breaks beyond the 4-byte prefix lane are flagged in an `ambiguous` mask
for the host to resolve exactly (rare; Cells.reconcile full-value compare).

Shapes are padded to buckets so jit traces once per bucket size, not per
batch (XLA static-shape discipline).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..storage.cellbatch import (DEATH_FLAGS, FLAG_COMPLEX_DEL,
                                 FLAG_EXPIRING, FLAG_PARTITION_DEL,
                                 FLAG_ROW_DEL, FLAG_TOMBSTONE, CellBatch)
from ..schema import COL_PARTITION_DEL, COL_ROW_DEL

_U32_MAX = jnp.uint32(0xFFFFFFFF)


def _le_pair(ah, al, bh, bl):
    """(ah,al) <= (bh,bl) as unsigned 64-bit pairs."""
    return (ah < bh) | ((ah == bh) & (al <= bl))


def _lt_pair(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def _seg_carry_pair(vh, vl, is_start):
    """Forward-fill the (vh, vl) value from each segment start across the
    segment: positions where is_start is True supply the value, others
    inherit the most recent start's value."""

    def combine(a, b):
        ah, al, a_s = a
        bh, bl, b_s = b
        h = jnp.where(b_s, bh, ah)
        l = jnp.where(b_s, bl, al)
        return h, l, a_s | b_s

    h, l, _ = jax.lax.associative_scan(combine, (vh, vl, is_start))
    return h, l


@jax.jit
def merge_reconcile_kernel(operands):
    """Core kernel. `operands` is a dict of arrays, all length N (padded):
      lanes:   uint32 [N, K]  identity lanes (column lane at K-3)
      valid:   uint32 [N]     0 for real cells, 1 for padding
      ts_h/ts_l: uint32       biased write timestamp (desc tie-break + shadow)
      death:   uint32         1 if record is any kind of deletion
      vp:      uint32         4-byte value prefix (tie-break)
      ldt:     int32          local deletion / expiry seconds
      expiring: uint32        1 if cell has TTL
      purge_h/purge_l: uint32 biased per-cell max-purgeable timestamp
      gc_before, now: int32 scalars
    Returns (perm, keep, ambiguous) — all length N.
    """
    lanes = operands["lanes"]
    N, K = lanes.shape
    ts_h, ts_l = operands["ts_h"], operands["ts_l"]
    death = operands["death"]
    vp = operands["vp"]

    # ---- 1. one big stable sort ------------------------------------------
    keys = [operands["valid"]]
    keys += [lanes[:, k] for k in range(K)]
    keys += [_U32_MAX - ts_h, _U32_MAX - ts_l,        # ts desc
             jnp.uint32(1) - death,                   # tombstone first
             _U32_MAX - vp]                           # larger value first
    idx = jnp.arange(N, dtype=jnp.uint32)
    out = jax.lax.sort(tuple(keys) + (idx,), num_keys=len(keys),
                       is_stable=True)
    perm = out[-1].astype(jnp.int32)

    g = lambda a: a[perm]
    lanes = lanes[perm]
    ts_h, ts_l = g(ts_h), g(ts_l)
    death, vp = g(death), g(vp)
    valid = g(operands["valid"]) == 0
    ldt = g(operands["ldt"])
    expiring = g(operands["expiring"]) == 1
    purge_h, purge_l = g(operands["purge_h"]), g(operands["purge_l"])

    # ---- 2. boundaries ----------------------------------------------------
    prev = jnp.concatenate([jnp.full((1, K), 0xFFFFFFFF, dtype=jnp.uint32),
                            lanes[:-1]], axis=0)
    diff = lanes != prev
    first = jnp.zeros(N, dtype=bool).at[0].set(True)
    part_new = first | diff[:, :4].any(axis=1)
    row_new = part_new | diff[:, 4:K - 3].any(axis=1)
    col_new = row_new | diff[:, K - 3]
    cell_new = col_new | diff[:, K - 2:].any(axis=1)

    col = lanes[:, K - 3]
    winner = cell_new & valid

    # ---- 3. deletion shadowing -------------------------------------------
    is_pd = col == COL_PARTITION_DEL
    is_rd = col == COL_ROW_DEL
    is_cd = g(operands["cdel"]) == 1
    zero = jnp.uint32(0)
    # partition deletions sort first in their partition; the partition-start
    # record is the pd winner when one exists
    pd_h = jnp.where(part_new & is_pd, ts_h, zero)
    pd_l = jnp.where(part_new & is_pd, ts_l, zero)
    pd_h, pd_l = _seg_carry_pair(pd_h, pd_l, part_new)
    # row deletions sort first in their row
    rd_h = jnp.where(row_new & is_rd, ts_h, zero)
    rd_l = jnp.where(row_new & is_rd, ts_l, zero)
    rd_h, rd_l = _seg_carry_pair(rd_h, rd_l, row_new)
    # effective row-scope deletion = max(pd, rd)
    use_pd = _lt_pair(rd_h, rd_l, pd_h, pd_l)
    del_h = jnp.where(use_pd, pd_h, rd_h)
    del_l = jnp.where(use_pd, pd_l, rd_l)
    # complex (collection) deletions sort first in their (row, column)
    cd_h = jnp.where(col_new & is_cd, ts_h, zero)
    cd_l = jnp.where(col_new & is_cd, ts_l, zero)
    cd_h, cd_l = _seg_carry_pair(cd_h, cd_l, col_new)
    use_cd = _lt_pair(del_h, del_l, cd_h, cd_l)
    cdel_h = jnp.where(use_cd, cd_h, del_h)
    cdel_l = jnp.where(use_cd, cd_l, del_l)

    plain = ~is_pd & ~is_rd & ~is_cd
    shadowed = jnp.where(
        plain, _le_pair(ts_h, ts_l, cdel_h, cdel_l),
        jnp.where(is_rd, _le_pair(ts_h, ts_l, pd_h, pd_l),
                  jnp.where(is_cd, _le_pair(ts_h, ts_l, del_h, del_l),
                            False)))

    # ---- 4. TTL expiry + purge -------------------------------------------
    now = operands["now"]
    gc_before = operands["gc_before"]
    expired = expiring & (ldt <= now)
    death_eff = (death == 1) | expired
    purgeable = _lt_pair(ts_h, ts_l, purge_h, purge_l)
    purged = death_eff & (ldt < gc_before) & purgeable

    keep = winner & ~shadowed & ~purged

    # ---- 5. ambiguous value ties (host resolves with full bytes) ---------
    same_meta = (~cell_new) & (ts_h == prev_eq(ts_h)) & (ts_l == prev_eq(ts_l)) \
        & (death == prev_eq(death)) & (vp == prev_eq(vp))
    ambiguous = same_meta & valid
    return perm, keep, ambiguous, expired, shadowed


def prev_eq(a):
    """a shifted by one (first element compares unequal)."""
    return jnp.concatenate([jnp.full((1,), ~a[0], dtype=a.dtype), a[:-1]])


# ----------------------------------------------------------------- wrapper --

def _bucket(n: int) -> int:
    """Pad to power-of-two buckets >= 1024 so jit compiles once per bucket."""
    b = 1024
    while b < n:
        b <<= 1
    return b


def merge_sorted_device(batches: list[CellBatch], gc_before: int = 0,
                        now: int = 0, purgeable_ts_fn=None) -> CellBatch:
    """Drop-in equivalent of storage.cellbatch.merge_sorted running the
    sort/reconcile on the default JAX device."""
    cat = CellBatch.concat(batches)
    n = len(cat)
    if n == 0:
        return cat
    N = _bucket(n)
    K = cat.n_lanes

    lanes = np.full((N, K), 0xFFFFFFFF, dtype=np.uint32)
    lanes[:n] = cat.lanes
    valid = np.ones(N, dtype=np.uint32)
    valid[:n] = 0
    with np.errstate(over="ignore"):
        uts = cat.ts.astype(np.uint64) ^ np.uint64(1 << 63)
    ts_h = np.zeros(N, dtype=np.uint32)
    ts_l = np.zeros(N, dtype=np.uint32)
    ts_h[:n] = (uts >> np.uint64(32)).astype(np.uint32)
    ts_l[:n] = (uts & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    death = np.zeros(N, dtype=np.uint32)
    death[:n] = (cat.flags & DEATH_FLAGS) != 0
    cdel = np.zeros(N, dtype=np.uint32)
    cdel[:n] = (cat.flags & FLAG_COMPLEX_DEL) != 0
    vp = np.zeros(N, dtype=np.uint32)
    vp[:n] = cat._value_prefix_lane()
    ldt = np.zeros(N, dtype=np.int32)
    ldt[:n] = cat.ldt
    expiring = np.zeros(N, dtype=np.uint32)
    expiring[:n] = (cat.flags & FLAG_EXPIRING) != 0

    if purgeable_ts_fn is not None:
        pts = purgeable_ts_fn(cat).astype(np.int64)
        with np.errstate(over="ignore"):
            upts = pts.astype(np.uint64) ^ np.uint64(1 << 63)
        purge_h = np.zeros(N, dtype=np.uint32)
        purge_l = np.zeros(N, dtype=np.uint32)
        purge_h[:n] = (upts >> np.uint64(32)).astype(np.uint32)
        purge_l[:n] = (upts & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    else:
        purge_h = np.full(N, 0xFFFFFFFF, dtype=np.uint32)
        purge_l = np.full(N, 0xFFFFFFFF, dtype=np.uint32)

    operands = {
        "lanes": jnp.asarray(lanes), "valid": jnp.asarray(valid),
        "ts_h": jnp.asarray(ts_h), "ts_l": jnp.asarray(ts_l),
        "death": jnp.asarray(death), "vp": jnp.asarray(vp),
        "cdel": jnp.asarray(cdel),
        "ldt": jnp.asarray(ldt), "expiring": jnp.asarray(expiring),
        "purge_h": jnp.asarray(purge_h), "purge_l": jnp.asarray(purge_l),
        "gc_before": jnp.int32(gc_before), "now": jnp.int32(now),
    }
    perm, keep, ambiguous, expired, shadowed = merge_reconcile_kernel(operands)
    perm = np.asarray(perm)
    keep = np.array(keep)          # writable copy: host fix-up mutates it
    ambiguous = np.asarray(ambiguous)
    expired = np.asarray(expired)
    shadowed = np.asarray(shadowed)

    # strip padding; padded entries sort last (valid is the primary key)
    perm_real = perm[:n]
    s = cat.apply_permutation(perm_real)
    keep = keep[:n]
    expired = expired[:n]
    # expired-TTL conversion (mirrors numpy reconcile step 2)
    s.flags[expired] |= FLAG_TOMBSTONE

    # host-exact value tie-break (device flagged the candidate runs);
    # mirrors the numpy path: winner moves to the largest full value, then
    # shadow/purge apply at the new winner (ts/death equal across the run,
    # so only the ldt-dependent purge needs re-evaluation)
    amb = ambiguous[:n]
    if amb.any():
        if purgeable_ts_fn is not None:
            pts_sorted = purgeable_ts_fn(cat).astype(np.int64)[perm_real]
        else:
            pts_sorted = None
        death_s = ((s.flags & DEATH_FLAGS) != 0)
        shadow_n = shadowed[:n]
        idxs = np.flatnonzero(amb)
        prev_i = -2
        runs = []
        for i in idxs:
            if i != prev_i + 1:
                runs.append([i - 1, i])
            else:
                runs[-1][1] = i
            prev_i = i
        _, _, cell_new = s.boundaries()
        for lo, hi in runs:
            if not cell_new[lo]:
                continue  # run of older duplicates below the winner
            best = max(range(lo, hi + 1), key=s.cell_value)
            keep[lo:hi + 1] = False
            purgeable = pts_sorted is None or s.ts[best] < pts_sorted[best]
            purged = bool(death_s[best]) and s.ldt[best] < gc_before \
                and purgeable
            keep[best] = not (shadow_n[best] or purged)
    out = s.apply_permutation(np.flatnonzero(keep))
    out.sorted = True
    # expired-TTL -> tombstone conversion drops the dead value (mirrors
    # the numpy path exactly)
    converted = ((out.flags & FLAG_EXPIRING) != 0) & \
        ((out.flags & FLAG_TOMBSTONE) != 0)
    return out.drop_values(converted)
