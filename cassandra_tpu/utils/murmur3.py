"""MurmurHash3 x64/128 — the partitioner and bloom-filter hash.

Semantics follow the reference's hasher used by Murmur3Partitioner
(reference: src/java/org/apache/cassandra/utils/MurmurHash.java:145
``hash3_x64_128``) and the token normalisation in
dht/Murmur3Partitioner.java (Long.MIN_VALUE is mapped to Long.MAX_VALUE so
the token space is (MIN, MAX]).

Two implementations:
  * ``hash128(data, seed)`` — scalar, exact, for keys at write/read time.
  * ``hash128_batch(keys)`` — numpy-vectorised over a padded uint8 matrix,
    used to hash many partition keys per call (bloom-filter builds, token
    computation during flush). A Pallas/TPU port is the natural next step
    since the state is 2 lanes of u64 math.
"""
from __future__ import annotations

import struct

import numpy as np

_MASK = 0xFFFFFFFFFFFFFFFF
_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK


def _fmix(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _MASK
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _MASK
    k ^= k >> 33
    return k


def hash128(data: bytes, seed: int = 0) -> tuple[int, int]:
    """MurmurHash3 x64/128. Returns (h1, h2) as unsigned 64-bit ints."""
    length = len(data)
    nblocks = length // 16
    h1 = seed & _MASK
    h2 = seed & _MASK

    for i in range(nblocks):
        k1, k2 = struct.unpack_from("<QQ", data, i * 16)
        k1 = (k1 * _C1) & _MASK
        k1 = _rotl64(k1, 31)
        k1 = (k1 * _C2) & _MASK
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & _MASK
        h1 = (h1 * 5 + 0x52DCE729) & _MASK
        k2 = (k2 * _C2) & _MASK
        k2 = _rotl64(k2, 33)
        k2 = (k2 * _C1) & _MASK
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & _MASK
        h2 = (h2 * 5 + 0x38495AB5) & _MASK

    # Tail: the reference XOR-accumulates SIGN-EXTENDED bytes
    # (MurmurHash.java:216-232, `(long) key.get(...)` without & 0xff), which
    # diverges from canonical murmur3 whenever a tail byte is >= 0x80. We
    # reproduce that exactly so tokens match Murmur3Partitioner.
    tail = data[nblocks * 16:]
    k1 = 0
    k2 = 0
    tl = len(tail)
    if tl >= 9:
        for i in range(tl - 1, 7, -1):
            sb = tail[i] - 256 if tail[i] >= 128 else tail[i]
            k2 ^= (sb << (8 * (i - 8))) & _MASK
        k2 = (k2 * _C2) & _MASK
        k2 = _rotl64(k2, 33)
        k2 = (k2 * _C1) & _MASK
        h2 ^= k2
    if tl > 0:
        for i in range(min(tl, 8) - 1, -1, -1):
            sb = tail[i] - 256 if tail[i] >= 128 else tail[i]
            k1 ^= (sb << (8 * i)) & _MASK
        k1 = (k1 * _C1) & _MASK
        k1 = _rotl64(k1, 31)
        k1 = (k1 * _C2) & _MASK
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _MASK
    h2 = (h2 + h1) & _MASK
    h1 = _fmix(h1)
    h2 = _fmix(h2)
    h1 = (h1 + h2) & _MASK
    h2 = (h2 + h1) & _MASK
    return h1, h2


def token_of(key: bytes) -> int:
    """Signed 64-bit token of a partition key.

    Mirrors Murmur3Partitioner.getToken: first 128-bit word as signed long,
    with Long.MIN_VALUE normalised to Long.MAX_VALUE."""
    h1, _ = hash128(key)
    t = h1 - (1 << 64) if h1 >= (1 << 63) else h1
    if t == -(1 << 63):
        t = (1 << 63) - 1
    return t


MIN_TOKEN = -(1 << 63)  # ring origin; no key hashes to it after normalisation


# ---------------------------------------------------------------- batch ----

def _pad_keys(keys: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Pack variable-length keys into a (n, maxlen) uint8 matrix + lengths."""
    n = len(keys)
    lens = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
    maxlen = int(lens.max()) if n else 0
    # round up to a 16-byte block boundary (+16 so tail logic has room)
    width = ((maxlen + 15) // 16 + 1) * 16
    mat = np.zeros((n, width), dtype=np.uint8)
    for i, k in enumerate(keys):
        mat[i, : len(k)] = np.frombuffer(k, dtype=np.uint8)
    return mat, lens


def hash128_batch(keys: list[bytes], seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Vectorised murmur3 x64/128 over many keys. Returns (h1, h2) uint64
    arrays."""
    if not keys:
        return np.zeros(0, np.uint64), np.zeros(0, np.uint64)
    mat, lens = _pad_keys(keys)
    return hash128_mat(mat, lens, seed)


def hash128_mat(mat: np.ndarray, lens: np.ndarray,
                seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Vectorised murmur3 over a pre-padded (n, width) uint8 matrix with
    per-row lengths; width must be a multiple of 16 with >= 16 bytes of
    padding beyond the longest row. Zero python loops over rows — the bulk
    generator and bloom builds feed millions of keys through here.

    All keys are processed in lock-step over the padded width; per-key block
    counts are honoured by masking (a block is only mixed into rows whose key
    is long enough). This is the same data-parallel shape a Pallas kernel
    would use."""
    lens = np.asarray(lens, dtype=np.int64)
    n, width = mat.shape
    blocks = mat.reshape(n, width // 16, 16)
    # little-endian u64 pairs per block (explicit dtype: host may be BE)
    as64 = blocks.view(np.dtype("<u8")).reshape(n, width // 16, 2)
    nblocks = (lens // 16).astype(np.int64)

    h1 = np.full(n, seed, dtype=np.uint64)
    h2 = np.full(n, seed, dtype=np.uint64)
    c1 = np.uint64(_C1)
    c2 = np.uint64(_C2)

    with np.errstate(over="ignore"):
        for b in range(width // 16):
            active = nblocks > b
            if not active.any():
                break
            k1 = as64[:, b, 0].copy()
            k2 = as64[:, b, 1].copy()
            k1 *= c1
            k1 = (k1 << np.uint64(31)) | (k1 >> np.uint64(33))
            k1 *= c2
            nh1 = h1 ^ k1
            nh1 = (nh1 << np.uint64(27)) | (nh1 >> np.uint64(37))
            nh1 += h2
            nh1 = nh1 * np.uint64(5) + np.uint64(0x52DCE729)
            k2 *= c2
            k2 = (k2 << np.uint64(33)) | (k2 >> np.uint64(31))
            k2 *= c1
            nh2 = h2 ^ k2
            nh2 = (nh2 << np.uint64(31)) | (nh2 >> np.uint64(33))
            nh2 += nh1
            nh2 = nh2 * np.uint64(5) + np.uint64(0x38495AB5)
            h1 = np.where(active, nh1, h1)
            h2 = np.where(active, nh2, h2)

        # Tails: XOR of SIGN-EXTENDED shifted bytes (reference
        # MurmurHash.java:216-232 semantics; see scalar impl above).
        tail_start = (nblocks * 16).astype(np.int64)
        tail_len = lens - tail_start
        idx = np.arange(16, dtype=np.int64)
        # (n, 16) gather of tail bytes, zero-padded
        gather_idx = tail_start[:, None] + idx[None, :]
        gather_idx = np.minimum(gather_idx, width - 1)
        tails = np.take_along_axis(mat, gather_idx, axis=1)
        valid = idx[None, :] < tail_len[:, None]
        stails = np.where(valid, tails.astype(np.int8).astype(np.int64), 0)
        shifts = (np.int64(8) * idx)[None, :]
        k1 = np.bitwise_xor.reduce(
            stails[:, :8] << shifts[:, :8], axis=1).astype(np.uint64)
        k2 = np.bitwise_xor.reduce(
            stails[:, 8:] << shifts[:, :8], axis=1).astype(np.uint64)

        has_k2 = tail_len >= 9
        k2 = (k2 * c2)
        k2 = (k2 << np.uint64(33)) | (k2 >> np.uint64(31))
        k2 = k2 * c1
        h2 = np.where(has_k2, h2 ^ k2, h2)
        has_k1 = tail_len > 0
        k1 = k1 * c1
        k1 = (k1 << np.uint64(31)) | (k1 >> np.uint64(33))
        k1 = k1 * c2
        h1 = np.where(has_k1, h1 ^ k1, h1)

        h1 ^= lens.astype(np.uint64)
        h2 ^= lens.astype(np.uint64)
        h1 += h2
        h2 += h1

        def fmix(k):
            k ^= k >> np.uint64(33)
            k *= np.uint64(0xFF51AFD7ED558CCD)
            k ^= k >> np.uint64(33)
            k *= np.uint64(0xC4CEB9FE1A85EC53)
            k ^= k >> np.uint64(33)
            return k

        h1 = fmix(h1)
        h2 = fmix(h2)
        h1 += h2
        h2 += h1
    return h1, h2


def tokens_of(keys: list[bytes]) -> np.ndarray:
    """Batch token computation. Returns int64 array of normalised tokens."""
    h1, _ = hash128_batch(keys)
    t = h1.astype(np.int64)
    return np.where(t == np.iinfo(np.int64).min, np.iinfo(np.int64).max, t)
