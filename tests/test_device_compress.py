"""Device-side LZ4 block compression (ops/device_compress.py): the
policy encoder's tri-identity (native C / numpy reference / jax
kernel), the fused segment scan kernel against segment_pack's host
transforms, the device pack replication of the compress-or-raw
placement rule, and the write-path integration — byte identity,
device↔host-fallback interleaving under adversarial completion order,
mid-compaction knob flips, kernel-failure fallback, and EIO unwind."""
from __future__ import annotations

import ctypes
import hashlib
import os
import time

import numpy as np
import pytest

from cassandra_tpu.compaction.task import CompactionTask
from cassandra_tpu.ops import device_compress as dc
from cassandra_tpu.ops.codec import (CompressionParams, SegmentPacker,
                                     get_compressor, lanes_shuffle)
from cassandra_tpu.ops.native import build as native_build
from cassandra_tpu.schema import TableParams, make_table
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
from cassandra_tpu.storage.sstable import writer as writer_mod
from cassandra_tpu.storage.sstable.compress_pool import CompressorPool
from cassandra_tpu.storage.table import ColumnFamilyStore
from cassandra_tpu.tools import bulk
from cassandra_tpu.utils import faultfs

_U8P = ctypes.POINTER(ctypes.c_uint8)


def _native_lz4(data: bytes, cap: int):
    """Direct native lz4_compress with an explicit output cap (the
    Compressor front-end always passes the generous max bound)."""
    lib = native_build.load()
    src = np.frombuffer(data, dtype=np.uint8) if data \
        else np.zeros(1, dtype=np.uint8)
    dst = np.empty(max(cap, 1), dtype=np.uint8)
    r = lib.lz4_compress(src.ctypes.data_as(_U8P), len(data),
                         dst.ctypes.data_as(_U8P), cap)
    return None if r < 0 else dst[:r].tobytes()


def _fixtures() -> dict[str, bytes]:
    rng = np.random.default_rng(42)
    fox = b"the quick brown fox jumps over the lazy dog " * 100
    meta_ish = np.zeros(25 * 600, dtype=np.uint8)
    meta_ish[::25] = rng.integers(0, 4, 600)          # 25-byte strides
    meta_ish[7::25] = np.arange(600) & 0xFF
    runs = b"".join(bytes([b]) * ln for b, ln in
                    zip(rng.integers(0, 256, 200),
                        rng.integers(1, 40, 200)))
    return {
        "fox": fox,
        "zeros": bytes(8192),
        "rand16k": rng.integers(0, 256, 16384, dtype=np.uint8).tobytes(),
        "empty": b"",
        "tiny": b"abc",
        "exact12": b"aaaaaaaaaaaa",                   # == mflimit floor
        "len13": b"abcabcabcabca",
        "meta_ish": meta_ish.tobytes(),
        "period25": (b"x" * 24 + b"|") * 300,
        "period44": fox[:44] * 90,
        "two_symbol": rng.choice([65, 66], 4096).astype(np.uint8).tobytes(),
        "low_entropy": rng.integers(0, 4, 8192, dtype=np.uint8).tobytes(),
        "runs": runs,
        "mixed": fox + rng.integers(0, 256, 4096,
                                    dtype=np.uint8).tobytes() + fox,
    }


# --------------------------------------------------- policy tri-identity --

def test_policy_tri_identity_and_roundtrip():
    """native lz4_compress == compress_np == compress_jax on every
    fixture, and the output decodes back through the syslib-backed
    decompressor (standard LZ4 block format)."""
    comp = get_compressor("LZ4Compressor")
    for name, data in _fixtures().items():
        ref = comp.compress(data)
        got_np = dc.compress_np(data)
        got_jax = dc.compress_jax(data)
        assert got_np == ref, f"{name}: numpy diverged from native"
        assert got_jax == ref, f"{name}: jax diverged from native"
        assert comp.uncompress(ref, len(data)) == data, name


def test_policy_cap_boundary_identical():
    """The abort decision uses the native encoder's conservative
    per-sequence `need` bound, not the exact emitted size: sweeping the
    cap through the boundary must flip compress→None at the SAME cap
    for native and replica (a one-byte disagreement here would flip a
    block's compressed/raw flag and change every downstream byte)."""
    for name, data in _fixtures().items():
        if not data:
            continue
        full = len(dc.compress_np(data))
        caps = {1, 5, len(data) // 2} | \
            set(range(max(full - 6, 1), full + 7))
        for cap in sorted(caps):
            n = _native_lz4(data, cap)
            r = dc.compress_np(data, cap)
            assert n == r, f"{name} cap={cap}: native={n is not None} " \
                           f"replica={r is not None}"


def test_tie_break_smallest_distance():
    """b'abab...' matches at every even distance with equal run length;
    the policy must pick d=2 (ascending candidate order)."""
    src = np.frombuffer(b"ab" * 64, dtype=np.uint8)
    bl, bd = dc.match_scan_np(src)
    assert bd[2] == 2 and bl[2] >= dc.MINMATCH
    # and the jax kernel agrees everywhere
    jbl, jbd = dc._scan_kernel(src)
    np.testing.assert_array_equal(np.asarray(jbl, dtype=np.int64), bl)
    np.testing.assert_array_equal(np.asarray(jbd, dtype=np.int64), bd)


# ------------------------------------------------------- segment kernel --

def _sorted_lanes(rng, n=512, k=3):
    rows = rng.integers(0, 1 << 32, (n, k), dtype=np.uint32)
    order = np.lexsort(tuple(rows[:, c] for c in range(k - 1, -1, -1)))
    return rows[order]


def test_segment_scan_kernel_matches_host_transforms():
    rng = np.random.default_rng(3)
    lanes = _sorted_lanes(rng)
    meta = rng.integers(0, 8, 25 * 200, dtype=np.uint8)
    planes, mbl, mbd, lbl, lbd, ok = dc.segment_scan_kernel(meta, lanes)
    assert bool(ok)
    planes_np = np.asarray(planes)
    np.testing.assert_array_equal(planes_np, lanes_shuffle(lanes))
    rbl, rbd = dc.match_scan_np(meta)
    np.testing.assert_array_equal(np.asarray(mbl, dtype=np.int64), rbl)
    np.testing.assert_array_equal(np.asarray(mbd, dtype=np.int64), rbd)
    rbl, rbd = dc.match_scan_np(planes_np)
    np.testing.assert_array_equal(np.asarray(lbl, dtype=np.int64), rbl)
    np.testing.assert_array_equal(np.asarray(lbd, dtype=np.int64), rbd)


def test_segment_scan_kernel_flags_order_violation():
    rng = np.random.default_rng(4)
    lanes = _sorted_lanes(rng)
    lanes[[10, 400]] = lanes[[400, 10]]   # u32-lex violation
    *_, ok = dc.segment_scan_kernel(
        rng.integers(0, 8, 100, dtype=np.uint8), lanes)
    assert not bool(ok)


def test_pack_device_segment_matches_segment_pack():
    """pack_device_segment replicates segment_pack verbatim: same
    total, per-block stored sizes, CRCs, and placed bytes, for every
    attempt combination and with the maxlen clamp engaged."""
    rng = np.random.default_rng(5)
    lanes = _sorted_lanes(rng, n=800)
    meta = np.zeros(25 * 800, dtype=np.uint8)
    meta[::25] = rng.integers(0, 4, 800)
    payload = rng.integers(97, 122, 6000, dtype=np.uint8)  # compressible
    packer = SegmentPacker.create(get_compressor("LZ4Compressor"))
    assert packer is not None and packer._cid == 1
    planes, mbl, mbd, lbl, lbd, ok = dc.segment_scan_kernel(meta, lanes)
    assert bool(ok)
    planes_np = np.asarray(planes)
    scans = ((np.asarray(mbl), np.asarray(mbd)),
             (np.asarray(lbl), np.asarray(lbd)))
    for maxlen in (1 << 62, 1200):
        for att in ((True,) * 3, (True, True, False),
                    (False, True, True), (True, False, False),
                    (False,) * 3):
            total, sizes, crcs, parts = dc.pack_device_segment(
                meta, planes_np, scans, payload, att, maxlen)
            out = np.zeros(meta.size + lanes.nbytes + payload.size + 64,
                           dtype=np.uint8)
            rtotal, rsizes, rraws, rcrcs = packer.pack(
                [meta, lanes, payload], list(att), maxlen,
                shuffle_block=1, lane_width=lanes.shape[1], out=out)
            assert total == rtotal, (maxlen, att)
            assert sizes == list(rsizes), (maxlen, att)
            assert crcs == list(rcrcs), (maxlen, att)
            assert b"".join(parts) == out[:rtotal].tobytes(), (maxlen, att)


def test_pack_device_segment_rejects_unsorted_lanes():
    """The device order check raises the same data-integrity error the
    native path does (ops/device_write.py re-raises on order_ok=False;
    this pins the contract at the kernel seam)."""
    rng = np.random.default_rng(6)
    lanes = _sorted_lanes(rng)
    lanes[[0, 100]] = lanes[[100, 0]]
    *_, ok = dc.segment_scan_kernel(
        np.zeros(50, dtype=np.uint8), lanes)
    assert not bool(ok)
    out = np.zeros(lanes.nbytes + 256, dtype=np.uint8)
    packer = SegmentPacker.create(get_compressor("LZ4Compressor"))
    with pytest.raises(ValueError, match="out of order"):
        packer.pack([np.zeros(50, dtype=np.uint8), lanes,
                     np.zeros(1, dtype=np.uint8)], [True] * 3,
                    1 << 62, 1, lanes.shape[1], out)


# ------------------------------------------------- write-path integration --

def _table(name: str):
    return make_table(
        "devcmp", name, pk=["id"], ck=["c"],
        cols={"id": "int", "c": "int", "v": "blob"},
        params=TableParams(compression=CompressionParams(
            "LZ4Compressor", chunk_length=16 * 1024)))


def _build_inputs(cfs, table, n_ssts=3, n_per=60_000, seed=9):
    rng = np.random.default_rng(seed)
    for gen in range(1, n_ssts + 1):
        pk = rng.integers(0, 300, n_per)
        ck = rng.integers(0, 100_000, n_per)
        text = rng.integers(97, 122, (n_per, 24), dtype=np.uint8)
        blob = rng.integers(0, 256, (n_per, 24), dtype=np.uint8)
        vals = np.where((pk % 2 == 0)[:, None], text, blob)
        ts = rng.integers(1, 1 << 40, n_per).astype(np.int64)
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=300)
        w.append(cb.merge_sorted([bulk.build_int_batch(table, pk, ck,
                                                       vals, ts)]))
        w.finish()


def _hashes(directory: str) -> dict:
    comps = ("Data.db", "Index.db", "Partitions.db", "Digest.crc32")
    out = {}
    for fn in sorted(os.listdir(directory)):
        p = os.path.join(directory, fn)
        if os.path.isfile(p) and any(fn.endswith(c) for c in comps):
            with open(p, "rb") as f:
                out[fn] = hashlib.sha256(f.read()).hexdigest()
    return out


def _compact(tmp_path, tag, table, n_per=60_000, **task_kw):
    d = str(tmp_path / tag)
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_inputs(cfs, table, n_per=n_per)
    cfs.reload_sstables()
    CompactionTask(cfs, cfs.tracker.view(), **task_kw).execute()
    h = _hashes(cfs.directory)
    for r in cfs.live_sstables():
        r.close()
    return h


def test_device_compress_identical_to_serial(tmp_path):
    table = _table("ident")
    serial = _compact(tmp_path, "serial", table, pipelined_io=False,
                      compress_pool=0, decode_ahead=False)
    devc = _compact(tmp_path, "devc", table, engine="device",
                    use_device=True, pipelined_io=True,
                    compress_pool=0, decode_ahead=False,
                    device_compress=True)
    assert serial and devc == serial


def test_device_host_interleave_adversarial_order(tmp_path, monkeypatch):
    """Device-packed and pool-compressed segments share one ordered io
    queue: an alternating per-segment gate interleaves the two job
    kinds, and delaying even segments makes successors complete FIRST.
    The drain must still be submit-ordered — bytes identical to
    serial."""
    table = _table("ileave")
    serial = _compact(tmp_path, "serial", table, pipelined_io=False,
                      compress_pool=0, decode_ahead=False)

    def delay(seq):
        if seq % 2 == 0:
            time.sleep(0.02)

    monkeypatch.setattr(writer_mod, "_TEST_SEGMENT_DELAY", delay)
    d = str(tmp_path / "mix")
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_inputs(cfs, table)
    cfs.reload_sstables()
    flips = [0]

    def gate():
        flips[0] += 1
        return flips[0] % 2 == 1   # device, host, device, ...

    cfs.device_compress_fn = gate
    pool = CompressorPool(2)
    try:
        CompactionTask(cfs, cfs.tracker.view(), engine="device",
                       use_device=True, pipelined_io=True,
                       compress_pool=pool, decode_ahead=False).execute()
    finally:
        pool.shutdown(timeout=5.0)
    assert flips[0] >= 2           # the gate really alternated
    assert _hashes(cfs.directory) == serial
    for r in cfs.live_sstables():
        r.close()


def test_device_compress_knob_flip_mid_compaction(tmp_path):
    """The writer re-reads the engine-scoped gate per segment: flipping
    `compaction_device_compress` off mid-compaction moves later
    segments to the host path with identical bytes."""
    table = _table("flip")
    # 3 x 100k cells: >= 4 full 64Ki-cell segments, so the flip after
    # two gate reads leaves later segments on the host path
    pinned = _compact(tmp_path, "pinned", table, n_per=100_000,
                      engine="device", use_device=True,
                      pipelined_io=True, compress_pool=0,
                      decode_ahead=False, device_compress=False)
    d = str(tmp_path / "flipped")
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_inputs(cfs, table, n_per=100_000)
    cfs.reload_sstables()
    calls = [0]

    def knob():
        calls[0] += 1
        return calls[0] <= 2       # device for two segments, then OFF

    cfs.device_compress_fn = knob
    CompactionTask(cfs, cfs.tracker.view(), engine="device",
                   use_device=True, pipelined_io=True,
                   compress_pool=0, decode_ahead=False).execute()
    assert calls[0] >= 3           # gate re-read per segment
    assert _hashes(cfs.directory) == pinned
    for r in cfs.live_sstables():
        r.close()


def test_kernel_failure_falls_back_per_segment(tmp_path, monkeypatch):
    """A raising scan kernel must not fail the compaction: the segment
    falls back to the host compress path (metric counted), output bytes
    unchanged."""
    from cassandra_tpu.service.metrics import GLOBAL as METRICS
    table = _table("fb")
    serial = _compact(tmp_path, "serial", table, pipelined_io=False,
                      compress_pool=0, decode_ahead=False)

    def boom(*a, **k):
        raise RuntimeError("injected kernel failure")

    monkeypatch.setattr(dc, "segment_scan_kernel", boom)
    before = METRICS.counter("compaction.device_compress_fallback")
    devc = _compact(tmp_path, "devc", table, engine="device",
                    use_device=True, pipelined_io=True,
                    compress_pool=0, decode_ahead=False,
                    device_compress=True)
    assert devc == serial
    assert METRICS.counter("compaction.device_compress_fallback") > before


def test_device_compress_eio_unwinds_with_inputs_live(tmp_path):
    """EIO injected at the compress checkpoint of the device-packed
    submit path (the same checkpoint the pool workers honour — the
    serial inline leg, like the serial host pack, has no compressor
    seam to fault): the task fails through the normal unwind —
    lifecycle txn rolled back, tmp components gone, inputs still live
    and serving."""
    table = _table("eio")
    d = str(tmp_path / "store")
    cfs = ColumnFamilyStore(table, d, commitlog=None)
    _build_inputs(cfs, table)
    cfs.reload_sstables()
    inputs_before = list(cfs.tracker.view())
    pool = CompressorPool(2)
    try:
        task = CompactionTask(cfs, inputs_before, engine="device",
                              use_device=True, pipelined_io=True,
                              compress_pool=pool, decode_ahead=False,
                              device_compress=True)
        with faultfs.inject("sstable.compress", "error"):
            with pytest.raises(OSError):
                task.execute()
    finally:
        faultfs.GLOBAL.disarm()
        pool.shutdown(timeout=5.0)
    assert list(cfs.tracker.view()) == inputs_before
    assert not [f for f in os.listdir(cfs.directory)
                if f.startswith("tmp-")]
    from cassandra_tpu.storage.chunk_cache import GLOBAL as chunk_cache
    chunk_cache.clear()
    pk = table.serialize_partition_key([4])
    assert len(cfs.read_partition(pk, now=int(time.time()))) > 0
    for r in cfs.live_sstables():
        r.close()
