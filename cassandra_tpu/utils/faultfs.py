"""Fault-injection filesystem checkpoints for the storage layer.

Reference counterpart: the reference exercises its FSError /
CorruptSSTableException machinery with byteman-injected faults in
dtests; this repo has no bytecode weaving, so the storage layer's file
I/O routes through thin named checkpoints instead. A test (or
scripts/chaos_storage.py) ARMS a failure point and the next I/O that
crosses the matching checkpoint fails exactly the way real hardware
would: EIO, a short read, a flipped bit, or a write torn after N bytes.

Failure points wired into the codebase (docs/fault-tolerance.md):

    sstable.open      component reads at SSTableReader open
    sstable.read      the Data.db segment pread in _decode_segment
    sstable.compress  the parallel-compress pool worker's pack job
                      (SSTableWriter._run_pack_job) — a worker EIO must
                      fail the writer like a serial compress error
    flush.write       SSTableWriter's data-write funnel (_write_sync) —
                      covers memtable flush AND compaction output
    commitlog.fsync   the fsync inside CommitLog._do_sync
    hints.read        the hint-file read in HintsService.dispatch
    stream.read       the sender pump's snapshot-chunk read
                      (cluster/stream_session.py)
    stream.net        the sender pump's chunk send — the only point
                      where the network modes (disconnect/latency)
                      bind; the path is the snapshot file behind the
                      chunk, so path_substr scopes by component
    stream.land       the receiver's staging writes AND the final
                      component landing (path = the component file, so
                      path_substr="TOC.txt" kills exactly the commit
                      point)

Modes:
    error        raise OSError(errno, ...) at the checkpoint (default
                 errno EIO)
    bitflip      flip one bit of the data crossing the checkpoint (the
                 CRC machinery downstream must detect it)
    short_read   deliver one byte less than requested
    torn_write   persist only the first `tear_bytes` bytes, then raise
    disconnect   drop the message crossing a network checkpoint (the
                 sender observes nothing — retransmit must recover)
    latency      delay the message crossing a network checkpoint by
                 `delay_s` seconds before delivering it intact

Arming is process-global (faults don't respect object boundaries any
more than disks do) and zero-cost when nothing is armed: every
checkpoint guards on `GLOBAL.active` first. `times`/`after` bound and
delay firing; `path_substr` scopes a point to matching paths so one
sstable's Data.db can be corrupted while its siblings stay healthy.
"""
from __future__ import annotations

import errno as _errno
import threading


class FaultPoint:
    """One armed failure point. Mutable counters are guarded by the
    registry lock."""

    __slots__ = ("point", "mode", "errno_", "times", "after",
                 "path_substr", "bit_offset", "tear_bytes", "delay_s",
                 "hits", "fires")

    def __init__(self, point: str, mode: str = "error",
                 errno_: int = _errno.EIO, times: int | None = None,
                 after: int = 0, path_substr: str | None = None,
                 bit_offset: int | None = None, tear_bytes: int = 0,
                 delay_s: float = 0.05):
        if mode not in ("error", "bitflip", "short_read", "torn_write",
                        "disconnect", "latency"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.point = point
        self.mode = mode
        self.errno_ = errno_
        self.times = times          # fire at most N times (None = forever)
        self.after = after          # skip the first N matching hits
        self.path_substr = path_substr
        self.bit_offset = bit_offset  # byte to flip (None = middle)
        self.tear_bytes = tear_bytes  # bytes persisted before the tear
        self.delay_s = delay_s        # latency-mode injected delay
        self.hits = 0
        self.fires = 0

    def make_error(self, path: str) -> OSError:
        return OSError(self.errno_,
                       f"injected fault at {self.point}", path or None)


class FaultRegistry:
    """Process-global registry of armed failure points."""

    def __init__(self):
        self._lock = threading.Lock()
        self._points: dict[str, FaultPoint] = {}

    # ------------------------------------------------------------- arming

    @property
    def active(self) -> bool:
        """Cheap guard for the hot paths: False ⇒ every checkpoint is a
        single attribute read."""
        return bool(self._points)

    def arm(self, point: str, mode: str = "error", **kw) -> FaultPoint:
        fp = FaultPoint(point, mode, **kw)
        with self._lock:
            self._points[point] = fp
        return fp

    def disarm(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._points.clear()
            else:
                self._points.pop(point, None)

    def armed(self, point: str) -> FaultPoint | None:
        return self._points.get(point)

    def fires(self, point: str) -> int:
        fp = self._points.get(point)
        return fp.fires if fp is not None else 0

    # ----------------------------------------------------------- matching

    def _take(self, point: str, path: str, modes: tuple) -> FaultPoint | None:
        """Consume one firing of `point` if it is armed in one of
        `modes` and matches `path`; None otherwise. Each checkpoint
        kind consumes only its own modes so a bitflip-armed point is
        never double-counted by the error check at the same site."""
        fp = self._points.get(point)
        if fp is None or fp.mode not in modes:
            return None
        if fp.path_substr and fp.path_substr not in path:
            return None
        with self._lock:
            fp.hits += 1
            if fp.hits <= fp.after:
                return None
            if fp.times is not None and fp.fires >= fp.times:
                return None
            fp.fires += 1
        return fp

    # -------------------------------------------------------- checkpoints

    def check(self, point: str, path: str = "") -> None:
        """Error-mode checkpoint: raise the injected OSError."""
        fp = self._take(point, path, ("error",))
        if fp is not None:
            raise fp.make_error(path)

    def on_read(self, point: str, path: str, data: bytes) -> bytes:
        """Whole-buffer read checkpoint (component opens, hint files):
        error raises; bitflip/short_read transform the bytes."""
        self.check(point, path)
        fp = self._take(point, path, ("bitflip", "short_read"))
        if fp is None or not data:
            return data
        if fp.mode == "short_read":
            return data[:max(len(data) - 1, 0)]
        buf = bytearray(data)
        i = fp.bit_offset if fp.bit_offset is not None else len(buf) // 2
        buf[min(i, len(buf) - 1)] ^= 0x01
        return bytes(buf)

    def on_pread(self, point: str, path: str, iovs: list, got: int) -> int:
        """Scatter-read checkpoint (the sstable segment pread): error
        raises; short_read shrinks the byte count the caller observed;
        bitflip flips one bit in the largest landed buffer (the CRC
        check downstream must turn it into corruption). Returns the
        (possibly reduced) byte count."""
        self.check(point, path)
        fp = self._take(point, path, ("bitflip", "short_read"))
        if fp is None:
            return got
        if fp.mode == "short_read":
            return max(got - 1, 0)
        target = max(iovs, key=lambda v: v.nbytes)
        if target.nbytes:
            i = fp.bit_offset if fp.bit_offset is not None \
                else target.nbytes // 2
            i = min(i, target.nbytes - 1)
            target[i] ^= 0x01
        return got

    def on_net(self, point: str, path: str = "") -> bool:
        """Network checkpoint (the stream sender's chunk send): error
        raises; latency sleeps `delay_s` and delivers; disconnect
        returns True — the caller must DROP the message silently (a
        dead wire acks nothing; only retransmit recovers)."""
        self.check(point, path)
        fp = self._take(point, path, ("latency",))
        if fp is not None and fp.delay_s > 0:
            import time
            time.sleep(fp.delay_s)
        fp = self._take(point, path, ("disconnect",))
        return fp is not None

    def on_write(self, point: str, path: str, mv):
        """Write checkpoint: returns (bytes_to_write, error_to_raise).
        error raises before anything lands; torn_write returns the
        prefix that DOES land plus the OSError the caller must raise
        after writing it; bitflip returns a corrupted copy."""
        self.check(point, path)
        fp = self._take(point, path, ("bitflip", "torn_write"))
        if fp is None:
            return mv, None
        buf = bytearray(mv)
        if fp.mode == "torn_write":
            tear = min(fp.tear_bytes, len(buf))
            return memoryview(bytes(buf[:tear])), fp.make_error(path)
        i = fp.bit_offset if fp.bit_offset is not None else len(buf) // 2
        if buf:
            buf[min(i, len(buf) - 1)] ^= 0x01
        return memoryview(bytes(buf)), None


GLOBAL = FaultRegistry()


# module-level conveniences (tests / chaos driver)

def arm(point: str, mode: str = "error", **kw) -> FaultPoint:
    return GLOBAL.arm(point, mode, **kw)


def disarm(point: str | None = None) -> None:
    GLOBAL.disarm(point)


def check(point: str, path: str = "") -> None:
    if GLOBAL.active:
        GLOBAL.check(point, path)


def on_net(point: str, path: str = "") -> bool:
    """True = drop the message (disconnect armed); may sleep (latency)
    or raise (error). Zero-cost when nothing is armed."""
    if GLOBAL.active:
        return GLOBAL.on_net(point, path)
    return False


class inject:
    """Context manager: arm on enter, disarm on exit.

        with faultfs.inject("sstable.read", "bitflip",
                            path_substr="Data.db"):
            ...
    """

    def __init__(self, point: str, mode: str = "error", **kw):
        self.point = point
        self.mode = mode
        self.kw = kw
        self.fp: FaultPoint | None = None

    def __enter__(self) -> FaultPoint:
        self.fp = GLOBAL.arm(self.point, self.mode, **self.kw)
        return self.fp

    def __exit__(self, *exc):
        GLOBAL.disarm(self.point)
