"""Partitioner breadth: Murmur3 (default), ByteOrdered (order-
preserving), Random (md5), Local — all mapping into the int64 token
space the columnar lanes use.

Reference: dht/Murmur3Partitioner.java, dht/ByteOrderedPartitioner.java,
dht/RandomPartitioner.java, dht/LocalPartitioner.java.
"""
import hashlib

import numpy as np
import pytest

from cassandra_tpu.utils import murmur3, partitioners


@pytest.fixture
def restore_partitioner():
    prev = partitioners.current()
    yield
    partitioners.set_current(prev)


def test_murmur3_is_default_and_exact():
    assert partitioners.current().name == "Murmur3Partitioner"
    # matches the long-standing token function bit for bit
    for k in (b"", b"a", b"hello", b"\x00\x01\x02\x03"):
        assert partitioners.token_of(k) == murmur3.token_of(k)


def test_byteordered_is_order_preserving():
    p = partitioners.get("ByteOrderedPartitioner")
    keys = [b"", b"\x00", b"a", b"ab", b"abcdefgh", b"abcdefghz", b"b",
            b"\xff" * 8]
    toks = [p.token(k) for k in keys]
    assert toks == sorted(toks)
    # vectorized path agrees with the scalar path
    n = len(keys)
    padded = np.zeros((n, 32), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int64)
    for i, k in enumerate(keys):
        padded[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
        lens[i] = len(k)
    assert partitioners.get("ByteOrderedPartitioner") \
        .tokens_mat(padded, lens).tolist() == toks


def test_random_partitioner_md5():
    p = partitioners.get("RandomPartitioner")
    k = b"key1"
    want = int.from_bytes(hashlib.md5(k).digest()[:8], "big") - (1 << 63)
    assert p.token(k) == want
    assert p.token(k) != murmur3.token_of(k)


def test_byteordered_end_to_end_ordered_scan(tmp_path,
                                             restore_partitioner):
    """A ByteOrdered cluster returns full-scan partitions in KEY order —
    the ordered-partitioner capability the reference reserves for
    ByteOrderedPartitioner."""
    partitioners.set_current("ByteOrderedPartitioner")
    from cassandra_tpu.cluster.node import LocalCluster
    c = LocalCluster(1, str(tmp_path), rf=1)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("CREATE TABLE ks.t (k text PRIMARY KEY, v int)")
        import random
        names = [f"key{i:03d}" for i in range(40)]
        shuffled = names[:]
        random.Random(7).shuffle(shuffled)
        for i, name in enumerate(shuffled):
            s.execute(f"INSERT INTO ks.t (k, v) VALUES ('{name}', {i})")
        rows = s.execute("SELECT k FROM ks.t").rows
        got = [r[0] for r in rows]
        assert got == sorted(got), "full scan must walk keys in order"
        assert sorted(got) == names
    finally:
        c.shutdown()
