"""Merkle tree over token ranges for anti-entropy repair.

Reference counterpart: utils/MerkleTree.java:72 (fixed-depth binary tree
over the token range; leaves hold hashes of the partitions they cover) and
repair/Validator.java:61 (adds partition digests in token order).

The tree is a flat array of 2^depth leaf hashes over an even split of the
(signed 64-bit) token space; inner hashes combine children. difference()
returns the token ranges whose subtrees disagree.
"""
from __future__ import annotations

import hashlib

import numpy as np

_SPAN = 1 << 64
_MIN = -(1 << 63)


class MerkleTree:
    def __init__(self, depth: int = 10):
        self.depth = depth
        self.n_leaves = 1 << depth
        self._leaf_data: list[bytes] = [b""] * self.n_leaves
        self.leaves: np.ndarray | None = None

    def leaf_of(self, token: int) -> int:
        return int(((token - _MIN) * self.n_leaves) // _SPAN)

    def add(self, token: int, digest: bytes) -> None:
        """Mix a partition digest into its leaf (order-insensitive mix so
        replicas can add in any order; the reference adds in token order —
        XOR keeps it commutative)."""
        i = self.leaf_of(token)
        cur = self._leaf_data[i]
        if not cur:
            self._leaf_data[i] = digest
        else:
            self._leaf_data[i] = bytes(a ^ b for a, b in zip(
                cur.ljust(16, b"\0"), digest.ljust(16, b"\0")))

    def seal(self) -> None:
        self.leaves = np.frombuffer(
            b"".join(h.ljust(16, b"\0")[:16] for h in self._leaf_data),
            dtype=np.uint8).reshape(self.n_leaves, 16)

    def root(self) -> bytes:
        if self.leaves is None:
            self.seal()
        return hashlib.md5(self.leaves.tobytes()).digest()

    def leaf_range(self, i: int) -> tuple[int, int]:
        """(start, end] token range of leaf i."""
        lo = _MIN + (i * _SPAN) // self.n_leaves
        hi = _MIN + ((i + 1) * _SPAN) // self.n_leaves - 1
        return lo, hi

    def difference(self, other: "MerkleTree") -> list[tuple[int, int]]:
        """Token ranges whose leaves differ (adjacent merged)."""
        if self.leaves is None:
            self.seal()
        if other.leaves is None:
            other.seal()
        assert self.depth == other.depth
        diff = (self.leaves != other.leaves).any(axis=1)
        out: list[tuple[int, int]] = []
        for i in np.flatnonzero(diff):
            lo, hi = self.leaf_range(int(i))
            if out and out[-1][1] + 1 == lo:
                out[-1] = (out[-1][0], hi)
            else:
                out.append((lo, hi))
        return out

    def serialize(self) -> bytes:
        if self.leaves is None:
            self.seal()
        return bytes([self.depth]) + self.leaves.tobytes()

    @classmethod
    def deserialize(cls, data: bytes) -> "MerkleTree":
        t = cls(depth=data[0])
        t.leaves = np.frombuffer(data, dtype=np.uint8,
                                 offset=1).reshape(t.n_leaves, 16)
        return t
