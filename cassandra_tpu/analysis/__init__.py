"""ctpulint — project-specific concurrency & invariant static analysis.

Reference counterpart: the reference ships build-time checkers enforcing
exactly this discipline (checkstyle + custom ant tasks: no synchronized
on monitors the simulator cannot intercept, no blocking calls on Netty
event loops, DatabaseDescriptor mutability audited by hand). Here the
same bug taxonomy — the one dominating every recent PR's post-review
hardening list — is machine-checked:

  lock-order        static lock-acquisition graph must be acyclic
  loop-blocking     nothing blocking reachable from the transport event
                    loop or under the gossip lock
  knob-wiring       every mutable=True config knob is actually wired
                    (on_change listener or per-use re-read site)
  worker-loops      daemon worker loops cannot die silently
  clock-discipline  clock-injectable / sim-patched modules never bind
                    the real clock

`walker.ProjectIndex` is the shared AST index (module discovery, call
graph approximation, lock sites, `# ctpulint: allow(...)` suppressions);
each check in `checks/` is a pure function `run(index) -> [Violation]`.
`scripts/check_static.py` is the tier-2 driver; the runtime half of the
lock-order story is `utils/lockwitness.py` (docs/static-analysis.md).
"""
from .report import Violation  # noqa: F401
from .walker import ProjectIndex, project_files  # noqa: F401
