"""Typed node configuration — the DatabaseDescriptor role.

Reference counterparts: config/Config.java (typed field catalog),
config/DatabaseDescriptor.java (validated access + mutable runtime
settings), config/DurationSpec.java / DataStorageSpec.java /
DataRateSpec.java (unit-string parsing: "10s", "16KiB", "64MiB/s").

Design: one frozen-shape dataclass of typed fields with reference
defaults; loading validates types, parses unit specs, and REJECTS unknown
keys (the reference fails startup on unrecognised yaml keys too). A
subset of fields is runtime-mutable (DatabaseDescriptor setters exposed
through nodetool/JMX in the reference; here through Settings.set, the
settings virtual table and nodetool) with change listeners so subsystems
(compaction throttle, guardrails, hint windows) react without restart.
"""
from __future__ import annotations

import dataclasses
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Callable


class ConfigError(Exception):
    pass


# ------------------------------------------------------------ unit specs --

_DUR_UNITS = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0,
              "m": 60.0, "h": 3600.0, "d": 86400.0}
_SIZE_UNITS = {"B": 1, "KiB": 1024, "MiB": 1024 ** 2, "GiB": 1024 ** 3}


def parse_duration(v, default_unit: str = "ms") -> float:
    """DurationSpec: '10s' / '200ms' / '1h' / bare number (default_unit).
    Returns seconds."""
    if isinstance(v, bool):
        raise ConfigError(f"invalid duration spec: {v!r}")
    if isinstance(v, (int, float)):
        return float(v) * _DUR_UNITS[default_unit]
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*(ns|us|ms|s|m|h|d)\s*", str(v))
    if not m:
        raise ConfigError(f"invalid duration spec: {v!r}")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


def parse_storage(v, default_unit: str = "B") -> int:
    """DataStorageSpec: '16KiB' / '32MiB' / bare number. Returns bytes."""
    if isinstance(v, bool):
        raise ConfigError(f"invalid storage spec: {v!r}")
    if isinstance(v, (int, float)):
        return int(v) * _SIZE_UNITS[default_unit]
    m = re.fullmatch(r"\s*(\d+)\s*(B|KiB|MiB|GiB)\s*", str(v))
    if not m:
        raise ConfigError(f"invalid storage spec: {v!r}")
    return int(m.group(1)) * _SIZE_UNITS[m.group(2)]


def parse_rate(v) -> float:
    """DataRateSpec: '64MiB/s' / bare number (MiB/s). Returns MiB/s."""
    if isinstance(v, bool):
        raise ConfigError(f"invalid rate spec: {v!r}")
    if isinstance(v, (int, float)):
        return float(v)
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*(B|KiB|MiB|GiB)/s\s*", str(v))
    if not m:
        raise ConfigError(f"invalid rate spec: {v!r}")
    return float(m.group(1)) * _SIZE_UNITS[m.group(2)] / _SIZE_UNITS["MiB"]


# A field whose yaml value is a unit spec string. kind: duration|storage|rate
def spec(kind: str, default, mutable: bool = False):
    return field(default=default,
                 metadata={"spec": kind, "mutable": mutable})


def mut(default):
    return field(default=default, metadata={"mutable": True})


@dataclass
class Config:
    """Typed catalog of node settings. Field names follow
    conf/cassandra.yaml; durations are SECONDS, sizes BYTES, rates MiB/s
    after parsing. Fields marked mutable may change at runtime."""

    # identity / topology (cassandra.yaml:10-25)
    cluster_name: str = "Test Cluster"
    num_tokens: int = 16
    partitioner: str = "Murmur3Partitioner"
    endpoint_snitch: str = "SimpleSnitch"
    dc: str = "dc1"
    rack: str = "rack1"

    # storage locations (cassandra.yaml:73-120)
    data_file_directories: list = field(default_factory=list)
    commitlog_directory: str = ""
    saved_caches_directory: str = ""
    hints_directory: str = ""

    # commitlog (cassandra.yaml:419-480)
    commitlog_sync: str = "periodic"            # periodic | batch | group
    commitlog_sync_period: float = spec("duration", 10.0)
    # group-commit window: minimum spacing between fsyncs under
    # commitlog_sync: group (GroupCommitLogService's
    # commitlog_sync_group_window); writers arriving inside the window
    # coalesce into the next sync. Seconds after parsing ("10ms").
    commitlog_sync_group_window: float = spec("duration", 0.010,
                                              mutable=True)
    commitlog_segment_size: int = spec("storage", 32 * 1024 * 1024)
    commitlog_compression: str = ""             # codec name or ""
    cdc_enabled: bool = False

    # memtable / flush (cassandra.yaml:903-916)
    memtable_flush_writers: int = 2
    memtable_cleanup_threshold: float = 0.25
    memtable_heap_space: int = spec("storage", 256 * 1024 * 1024)
    # token-range shards per memtable (TrieMemtable shard count role):
    # 0 = auto (8 with the write fast lane on, 1 with it off)
    memtable_shards: int = 0

    # compaction (cassandra.yaml:1217-1250)
    concurrent_compactors: int = mut(1)
    # compressor-worker pool for the bulk write path (compaction +
    # flush share it; storage/sstable/compress_pool.py): segments
    # compress concurrently and re-sequence through an ordered
    # completion queue, so output bytes are identical for any size.
    # 0 = auto (one worker per core, capped); hot-resizable.
    compaction_compressor_threads: int = mut(0)
    # mesh execution mode of the data plane (docs/multichip.md):
    # compaction tasks and large batched/range reads shard by
    # count-weighted token-range boundaries and fan across N mesh
    # lanes (jax devices for the device engine, GIL-releasing host
    # threads for the native/numpy engines). Output bytes are
    # identical to the serial paths for any N. 0 = off; hot-reloadable.
    compaction_mesh_devices: int = mut(0)
    # decode-ahead prefetch: a compaction helper thread decodes round
    # k+1's input segments while round k merges and its output
    # compresses (the LUDA decode/merge overlap; compaction/task.py).
    # Strictly handshaked, so round boundaries — and output bytes —
    # are identical either way. Engine-scoped like
    # compaction_mesh_devices and hot-reloadable: tasks re-read it
    # every round, so a mid-compaction flip stops (or restarts) the
    # prefetch thread at the next round boundary. Default on; the
    # device engine's serial round loop keeps its own submit/collect
    # pipelining instead.
    compaction_decode_ahead: bool = mut(True)
    # device-side block compression (ops/device_compress.py): device-
    # resident compaction rounds hand the host segments ALREADY
    # LZ4-compressed by the policy encoder's fused jax kernel, leaving
    # the host io thread a pwrite pump. Output bytes are identical on
    # or off (the native packer runs the same deterministic policy) —
    # this knob only moves the compress work between device and host.
    # Engine-scoped and hot-reloadable: the writer re-reads it per
    # segment, so a mid-compaction flip takes effect at the next
    # segment boundary. Only device-resident tasks consult it.
    compaction_device_compress: bool = mut(True)
    # device predicate/aggregate kernels for analytical scans
    # (ops/device_scan.py): scan_filtered evaluates pushdown predicates
    # with the jitted key-compare kernels instead of the numpy host
    # reference. Results are identical on or off (the host reference is
    # pinned bit-identical by check_scan_ab.py) — the knob only moves
    # the mask/fold work between device and host. Engine-scoped and
    # hot-reloadable: the scan consults it PER SEGMENT, so a mid-scan
    # flip takes effect at the next segment boundary.
    scan_device_filter: bool = mut(True)
    compaction_throughput: float = spec("rate", 64.0, mutable=True)
    # modern-yaml name for the same throttle (DataRateSpec
    # compaction_throughput_mib_per_sec). Negative = unset: the engine
    # falls back to compaction_throughput; setting either at runtime
    # reaches the live limiter.
    compaction_throughput_mib_per_sec: float = spec("rate", -1.0,
                                                    mutable=True)
    sstable_preemptive_open_interval: int = spec("storage",
                                                 50 * 1024 * 1024)

    # streaming / hints (cassandra.yaml / hints section); both throughput
    # knobs feed the stream sender's token bucket (cluster/
    # stream_session.py), hot-reloadable via the Node settings listeners
    stream_throughput_outbound: float = spec("rate", 24.0, mutable=True)
    inter_dc_stream_throughput_outbound: float = spec("rate", 24.0,
                                                      mutable=True)
    hinted_handoff_enabled: bool = mut(True)
    max_hint_window: float = spec("duration", 3 * 3600.0, mutable=True)
    hints_flush_period: float = spec("duration", 10.0)

    # request timeouts (cassandra.yaml:1320-1360), mutable like
    # DatabaseDescriptor.setReadRpcTimeout etc.
    read_request_timeout: float = spec("duration", 5.0, mutable=True)
    range_request_timeout: float = spec("duration", 10.0, mutable=True)
    write_request_timeout: float = spec("duration", 2.0, mutable=True)
    counter_write_request_timeout: float = spec("duration", 5.0,
                                                mutable=True)
    # ctpulint: allow(knob-wiring, reason=paxos contention backoff is attempt-count bounded today (cluster/paxos.py); the knob binds when contention waits become deadline-based)
    cas_contention_timeout: float = spec("duration", 1.0, mutable=True)
    # ctpulint: allow(knob-wiring, reason=TRUNCATE executes synchronously against local stores plus a fire-and-forget ring broadcast - there is no blocking wait to bound yet)
    truncate_request_timeout: float = spec("duration", 60.0, mutable=True)
    # ctpulint: allow(knob-wiring, reason=yaml-parity blanket alias; the wired per-operation knobs (read/write/range/counter_write_request_timeout) are the operative controls and the proxy.timeout blanket setter covers test use)
    request_timeout: float = spec("duration", 10.0, mutable=True)

    # failure detection / gossip
    phi_convict_threshold: float = mut(8.0)
    gossip_interval: float = spec("duration", 1.0)

    # native transport
    native_transport_port: int = 9042
    native_transport_max_frame_size: int = spec("storage",
                                                16 * 1024 * 1024)
    # ctpulint: allow(knob-wiring, reason=the event-loop server bounds load by in-flight REQUESTS (the permit gate) not connection count; a per-connection cap adds nothing until per-IP accounting exists. Default -1 is disabled.)
    native_transport_max_concurrent_connections: int = mut(-1)
    # event-loop front door (transport/server.py): selector threads
    # multiplexing all client sockets (Netty boss/worker role) and the
    # bounded request-dispatch executor decoupling protocol I/O from
    # query execution (Dispatcher.java role)
    native_transport_event_loops: int = 2
    native_transport_max_threads: int = 4
    # admission control: permits bounding in-flight (queued + executing)
    # requests — exhaustion answers OVERLOADED instead of queueing;
    # <= 0 disables the gate. Hot-reloadable.
    native_transport_max_concurrent_requests: int = mut(256)
    # per-client request rate limit in ops/s (4.1's
    # native_transport_rate_limiting role); 0 disables. Hot-reloadable
    # like compaction_throughput_mib_per_sec.
    native_transport_rate_limit_ops: int = mut(0)
    # prepared-statement registry LRU bound, in STATEMENTS (the
    # reference's prepared_statements_cache_size is MiB-denominated;
    # a count is the honest unit for this in-memory registry).
    # <= 0 = unbounded. Hot-reloadable; eviction counts
    # prepared_statements.evicted and an EXECUTE against an evicted id
    # returns the v4/v5 UNPREPARED error so drivers re-prepare.
    prepared_statements_cache_size: int = mut(1024)

    # internode
    storage_port: int = 7000
    internode_compression: str = "none"         # none | all | dc
    # verb-dispatch pool width per node (cluster/messaging.py): inbound
    # verb handlers execute on N pool workers behind the distributor
    # thread, so replica-side verbs scale with cores instead of
    # serializing behind one fsync-bound handler; response callbacks
    # stay ordered on the distributor. 0 = auto (one worker per core,
    # capped — every in-process node runs its own pool). Hot-resizable;
    # node shutdown withdraws the demand with the pool.
    internode_dispatch_threads: int = mut(0)

    # caches (cassandra.yaml key/row/counter cache section)
    key_cache_size: int = spec("storage", 50 * 1024 * 1024, mutable=True)
    row_cache_size: int = spec("storage", 0, mutable=True)
    # modern MiB-count knob for the shared row cache
    # (storage/row_cache.py). Negative = unset: fall back to a non-zero
    # row_cache_size, then the built-in default; 0 disables caching
    # even for tables that opted in via WITH caching.
    row_cache_size_mib: int = mut(-1)
    # ctpulint: allow(knob-wiring, reason=the counter-shard cache (cluster/counters.py) is unbounded-small per leader today; the byte cap binds when it grows an LRU)
    counter_cache_size: int = spec("storage", 25 * 1024 * 1024,
                                   mutable=True)
    # ctpulint: allow(knob-wiring, reason=the engine does not own an AutoSavingCache instance - storage/saved_caches.py takes period= from whoever constructs it (tests/operators); the knob binds when the engine grows a saver)
    cache_save_period: float = spec("duration", 14400.0, mutable=True)

    # failure handling (cassandra.yaml disk_failure_policy /
    # commit_failure_policy; storage/failures.py validates values and
    # reacts to runtime changes). Defaults diverge from the reference's
    # stop/stop deliberately: best_effort quarantines corrupt sstables
    # and keeps serving, ignore preserves the pre-policy commitlog
    # behavior — docs/fault-tolerance.md discusses the trade.
    disk_failure_policy: str = mut("best_effort")
    commit_failure_policy: str = mut("ignore")

    # security
    authenticator: str = "AllowAllAuthenticator"
    authorizer: str = "AllowAllAuthorizer"
    network_authorizer: str = "AllowAllNetworkAuthorizer"
    cidr_authorizer: str = "AllowAllCIDRAuthorizer"
    auth_cache_validity: float = spec("duration", 2.0, mutable=True)

    # misc operations
    incremental_backups: bool = mut(False)
    auto_snapshot: bool = True
    snapshot_before_compaction: bool = False
    # ctpulint: allow(knob-wiring, reason=byte-denominated batch thresholds have no serialized-size checkpoint on the batch path yet; the statement-count guardrails (guardrails.batch_statements_warn/fail) are the active control)
    batch_size_warn_threshold: int = spec("storage", 5 * 1024,
                                          mutable=True)
    # ctpulint: allow(knob-wiring, reason=same as batch_size_warn_threshold - no serialized-size checkpoint yet)
    batch_size_fail_threshold: int = spec("storage", 50 * 1024,
                                          mutable=True)
    tombstone_warn_threshold: int = mut(1000)
    tombstone_failure_threshold: int = mut(100_000)
    column_index_size: int = spec("storage", 64 * 1024)
    trace_probability: float = mut(0.0)
    slow_query_log_timeout: float = spec("duration", 0.5, mutable=True)
    # bounded ring of slow-query entries kept for the
    # system_views.slow_queries vtable (service/monitoring.py); the
    # capacity is hot-reloadable like the threshold
    slow_query_log_entries: int = mut(100)
    # diagnostic event bus (service/diagnostics.py,
    # DiagnosticEventService role): OFF by default like the reference's
    # diagnostic_events_enabled — publish sites cost one branch while
    # disabled. Hot-reloadable; the flight recorder folds published
    # events regardless of when the knob flips.
    diagnostic_events_enabled: bool = mut(False)
    # metrics-history sampler (service/history.py, the workload
    # observatory): OFF by default — while disabled no sampler thread
    # exists and nothing is captured (the diagnostic-bus zero-cost
    # rule). Hot-reloadable; flipping on starts the engine's sampler,
    # flipping off stops it (retained rings survive the flip so the
    # history up to the stop stays queryable).
    metrics_history_enabled: bool = mut(False)
    # fixed sampling interval for the raw ring ("10s"); hot-reloadable
    # — the running sampler picks the new period up on its next tick.
    # The raw ring holds 360 samples (1 h at the default) and every 30
    # raw samples downsample into one coarse bucket (288 kept ≈ 24 h),
    # min/max/last/sum-preserving.
    metrics_history_interval: float = spec("duration", 10.0,
                                           mutable=True)
    # adaptive compaction controller (control/loop.py, ROADMAP item 1):
    # the observe/decide/actuate loop over the metrics-history rings and
    # amplification gauges. OFF by default — while disabled no decision
    # thread exists and nothing is classified (the diagnostic-bus
    # zero-cost rule); `tick()` stays callable on demand. ENGINE-scoped
    # like metrics_history_enabled: each engine owns its controller.
    adaptive_compaction_enabled: bool = mut(False)
    # fixed decision interval ("30s"); hot-reloadable — a parked loop
    # wakes and applies the new period immediately.
    adaptive_compaction_interval: float = spec("duration", 30.0,
                                               mutable=True)
    # per-table cooldown after an applied strategy change: no further
    # strategy change for the table inside this window (the anti-flap
    # half of the hysteresis policy, docs/adaptive-compaction.md).
    adaptive_compaction_cooldown: float = spec("duration", 300.0,
                                               mutable=True)
    # consecutive ticks a CANDIDATE regime must persist before the
    # controller actuates it (the confirmation half of hysteresis).
    adaptive_compaction_confirm_ticks: int = mut(2)
    # continuous wall-clock profiler (service/sampler.py, observability
    # layer 6): the always-on low-overhead ring. OFF by default —
    # while no engine demands it no sampler thread exists and nothing
    # is captured (the diagnostic-bus zero-cost rule); on-demand
    # sessions (`nodetool profiler start`) run regardless of the knob,
    # and `sample_once()` stays callable. The sampler is PROCESS-global
    # (threads are process-wide), so the knob follows the bus demand
    # pattern: each engine adds/withdraws only its own demand.
    profiler_enabled: bool = mut(False)
    # sampling period for the wall-clock profiler ("50ms" = 20 Hz);
    # hot-reloadable — a parked sampler wakes and applies the new
    # period immediately. Floored at 5 ms so a zero knob cannot boot a
    # busy-spin sampler.
    profiler_interval: float = spec("duration", 0.05, mutable=True)
    # retrace sentinel (service/profiling.py registry): a device
    # program whose by-shape compile count crosses this budget
    # publishes a `profile.retrace` diagnostic event and counts every
    # further recompile in `profile.retraces` — shape-bucket churn is
    # caught the tick it happens. <= 0 disables the sentinel.
    # Process-global like the registry (last writer wins across
    # co-hosted engines, same as the shared device).
    profiler_retrace_budget: int = mut(16)
    # bound on ColumnFamilyStore.compaction_history (newest kept):
    # the per-compaction stats ring behind compactionhistory /
    # system_views.compaction_history. <= 0 = unbounded (the
    # pre-bound behavior). Hot-reloadable per store.
    compaction_history_entries: int = mut(256)
    # SLO layer (service/slo.py): {objective name: p99 target ms}
    # overrides/additions for the engine's SLO registry. Hot-reloadable
    # — the saturation matrix retargets per leg through this knob;
    # naming a histogram with no existing objective registers a new
    # objective over it (per-CL rows like client_requests.read.quorum).
    slo_targets: dict = field(default_factory=dict,
                              metadata={"mutable": True})

    # guardrail overrides (db/guardrails/GuardrailsOptions.java) — passed
    # through to storage/guardrails.py field-for-field
    guardrails: dict = field(default_factory=dict)

    # free-form transparent data encryption block (storage/encryption.py)
    transparent_data_encryption: dict = field(default_factory=dict)

    # ------------------------------------------------------------- load --

    @classmethod
    def load(cls, raw: dict) -> "Config":
        """Validate + coerce a raw dict (parsed yaml/json). Unknown keys
        and mis-typed values raise ConfigError (startup must fail loudly,
        DatabaseDescriptor.applyAll behavior)."""
        fields = {f.name: f for f in dataclasses.fields(cls)}
        out = {}
        for k, v in raw.items():
            f = fields.get(k)
            if f is None:
                raise ConfigError(f"unknown config key: {k!r}")
            out[k] = cls._coerce(f, v)
        return cls(**out)

    @staticmethod
    def _coerce(f: dataclasses.Field, v: Any):
        kind = f.metadata.get("spec")
        try:
            if kind == "duration":
                return parse_duration(v)
            if kind == "storage":
                return parse_storage(v)
            if kind == "rate":
                return parse_rate(v)
            if f.type in ("int", int):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ConfigError(f"{f.name}: expected int, got {v!r}")
                return int(v)
            if f.type in ("float", float):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ConfigError(
                        f"{f.name}: expected number, got {v!r}")
                return float(v)
            if f.type in ("bool", bool):
                if not isinstance(v, bool):
                    raise ConfigError(f"{f.name}: expected bool, got {v!r}")
                return v
            if f.type in ("str", str):
                if not isinstance(v, str):
                    raise ConfigError(f"{f.name}: expected str, got {v!r}")
                return v
            if f.type in ("list", list):
                if not isinstance(v, list):
                    raise ConfigError(f"{f.name}: expected list, got {v!r}")
                return list(v)
            if f.type in ("dict", dict):
                if not isinstance(v, dict):
                    raise ConfigError(f"{f.name}: expected dict, got {v!r}")
                return dict(v)
        except ConfigError:
            raise
        except Exception as e:
            raise ConfigError(f"{f.name}: {e}") from e
        return v

    def mutable_fields(self) -> set:
        return {f.name for f in dataclasses.fields(self)
                if f.metadata.get("mutable")}


class Settings:
    """Runtime settings surface over a Config: typed get/set with change
    listeners. The reference exposes these via JMX/nodetool (e.g.
    `nodetool setcompactionthroughput`) and the system_views.settings
    virtual table; both route through here."""

    def __init__(self, config: Config | None = None):
        self.config = config or Config()
        self._mutable = self.config.mutable_fields()
        self._fields = {f.name: f for f in dataclasses.fields(Config)}
        self._listeners: dict[str, list[Callable]] = {}
        self._lock = threading.Lock()

    def get(self, name: str):
        if name not in self._fields:
            raise ConfigError(f"unknown setting: {name!r}")
        return getattr(self.config, name)

    def set(self, name: str, value, source: str = "operator") -> None:
        """Hot-set a mutable setting (validated/coerced like load).
        `source` names the ACTOR for the config.reload diagnostic event:
        "operator" (nodetool / settings vtable, the default) or
        "controller" (the adaptive compaction loop) — flight-recorder
        bundles must distinguish human from controller actuation."""
        f = self._fields.get(name)
        if f is None:
            raise ConfigError(f"unknown setting: {name!r}")
        if name not in self._mutable:
            raise ConfigError(f"setting {name!r} is not mutable at runtime")
        coerced = Config._coerce(f, value)
        with self._lock:
            old = getattr(self.config, name)
            setattr(self.config, name, coerced)
            listeners = list(self._listeners.get(name, []))
        for cb in listeners:
            cb(coerced)
        # hot knob reloads are diagnostic events (the flight recorder
        # wants "what changed right before it broke — and WHO changed
        # it"); no-op while the bus is disabled
        from .service import diagnostics
        diagnostics.publish("config.reload", name=name,
                            value=repr(coerced), old=repr(old),
                            actor=source)

    def on_change(self, name: str, cb: Callable) -> None:
        if name not in self._fields:
            raise ConfigError(f"unknown setting: {name!r}")
        with self._lock:
            self._listeners.setdefault(name, []).append(cb)

    def remove_listener(self, name: str, cb: Callable) -> None:
        """Unregister (engine/proxy close paths — a Settings may outlive
        one engine instance across in-process restarts)."""
        with self._lock:
            subs = self._listeners.get(name, [])
            if cb in subs:
                subs.remove(cb)

    def all(self) -> list[tuple[str, str, bool]]:
        """(name, rendered value, mutable) rows — the settings vtable."""
        rows = []
        for name in sorted(self._fields):
            v = getattr(self.config, name)
            rows.append((name, repr(v) if isinstance(v, (dict, list))
                         else str(v), name in self._mutable))
        return rows
