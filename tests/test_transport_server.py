"""Event-loop native-protocol server: v5 framing edge cases, admission
control (permits / overload signals / per-client rate limiting),
prepared-statement LRU + UNPREPARED, shutdown and slow-consumer
behavior (cassandra_tpu/transport/; docs/native-transport.md).

The happy-path wire conformance lives in test_native_protocol.py and
runs unchanged against this server — these tests pin the parts the
thread-per-connection predecessor could not: bounded in-flight
requests, shedding instead of queueing, fixed thread count at high
connection counts, and framing corruption answered with PROTOCOL
errors instead of hangs."""
import socket
import struct
import threading
import time

import pytest

from cassandra_tpu.client import Cluster, DriverError
from cassandra_tpu.schema import Schema
from cassandra_tpu.service.metrics import GLOBAL as METRICS
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.transport import frame as fr
from cassandra_tpu.transport.admission import OverloadSignals, PermitGate
from cassandra_tpu.transport.server import CQLServer


@pytest.fixture
def server(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch")
    srv = CQLServer(eng)
    yield eng, srv
    srv.close()
    eng.close()


# ---------------------------------------------------------- raw helpers --

def _read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError(f"EOF after {len(buf)}/{n} bytes")
        buf += chunk
    return bytes(buf)


def _read_envelope_legacy(sock):
    hdr = _read_exact(sock, 9)
    (length,) = struct.unpack(">I", hdr[5:9])
    body = _read_exact(sock, length) if length else b""
    _ver, _flags, stream, op = struct.unpack(">BBhB", hdr[:5])
    return stream, op, body


def _read_envelope_v5(sock, buf: bytearray):
    """Reassemble one envelope from v5 segments."""
    while True:
        if len(buf) >= 9:
            (length,) = struct.unpack_from(">I", buf, 5)
            if len(buf) >= 9 + length:
                hdr = bytes(buf[:9])
                body = bytes(buf[9:9 + length])
                del buf[:9 + length]
                _v, _f, stream, op = struct.unpack(">BBhB", hdr[:5])
                return stream, op, body
        seg_hdr = _read_exact(sock, 6)
        plen, _sc = fr.decode_segment_header(seg_hdr)
        payload = _read_exact(sock, plen + 4)
        assert int.from_bytes(payload[plen:], "little") \
            == fr._crc32_v5(payload[:plen])
        buf += payload[:plen]


def _startup(sock, version: int = 4) -> None:
    body = struct.pack(">H", 1) + fr._string("CQL_VERSION") \
        + fr._string("3.4.5")
    sock.sendall(struct.pack(">BBhBI", version, 0, 0, fr.OP_STARTUP,
                             len(body)) + body)
    _stream, op, _body = _read_envelope_legacy(sock)   # READY is legacy
    assert op == fr.OP_READY


def _query_envelope(query: str, stream: int, version: int = 4) -> bytes:
    body = fr._long_string(query) + struct.pack(">H", 1)
    if version >= 5:
        body += struct.pack(">I", 0)
    else:
        body += b"\x00"
    return struct.pack(">BBhBI", version, 0, stream, fr.OP_QUERY,
                       len(body)) + body


def _connect(port: int) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


# ------------------------------------------------------ v5 framing edges --

def test_v5_envelope_spans_non_self_contained_segments(server):
    """One envelope split across two non-self-contained segments must
    reassemble (CQLMessageHandler's accumulating path)."""
    _eng, srv = server
    sock = _connect(srv.port)
    _startup(sock, version=5)
    env = _query_envelope("SELECT * FROM system.local", 3, version=5)
    half = len(env) // 2
    sock.sendall(fr.encode_segment(env[:half], self_contained=False))
    sock.sendall(fr.encode_segment(env[half:], self_contained=False))
    buf = bytearray()
    stream, op, _body = _read_envelope_v5(sock, buf)
    assert (stream, op) == (3, fr.OP_RESULT)
    sock.close()


def test_v5_several_envelopes_in_one_segment(server):
    """The inverse packing: two whole envelopes inside one
    self-contained segment both get answered."""
    _eng, srv = server
    sock = _connect(srv.port)
    _startup(sock, version=5)
    env_a = _query_envelope("SELECT * FROM system.local", 11, version=5)
    env_b = _query_envelope("SELECT * FROM system.local", 12, version=5)
    sock.sendall(fr.encode_segment(env_a + env_b, self_contained=True))
    buf = bytearray()
    got = {_read_envelope_v5(sock, buf)[0] for _ in range(2)}
    assert got == {11, 12}
    sock.close()


def test_v5_header_crc_corruption_protocol_error_not_hang(server):
    """A corrupted CRC24 segment header must answer a PROTOCOL error
    and close — never hang the connection or the loop."""
    _eng, srv = server
    sock = _connect(srv.port)
    _startup(sock, version=5)
    env = _query_envelope("SELECT * FROM system.local", 1, version=5)
    seg = bytearray(fr.encode_segment(env))
    seg[3] ^= 0xFF                       # first CRC24 byte
    sock.sendall(bytes(seg))
    buf = bytearray()
    _stream, op, body = _read_envelope_v5(sock, buf)
    assert op == fr.OP_ERROR
    (code,) = struct.unpack_from(">i", body, 0)
    assert code == fr.ERR_PROTOCOL
    with pytest.raises(EOFError):        # server closed after the error
        _read_exact(sock, 1)
    sock.close()


def test_v5_payload_crc_corruption_protocol_error(server):
    _eng, srv = server
    sock = _connect(srv.port)
    _startup(sock, version=5)
    env = _query_envelope("SELECT * FROM system.local", 1, version=5)
    seg = bytearray(fr.encode_segment(env))
    seg[7] ^= 0xFF                       # second payload byte
    sock.sendall(bytes(seg))
    buf = bytearray()
    _stream, op, body = _read_envelope_v5(sock, buf)
    assert op == fr.OP_ERROR
    (code,) = struct.unpack_from(">i", body, 0)
    assert code == fr.ERR_PROTOCOL
    with pytest.raises(EOFError):
        _read_exact(sock, 1)
    sock.close()


def test_interleaved_streams_on_one_connection(server):
    """Two requests written back-to-back on different stream ids both
    get answered, matched by stream id (the event-loop server executes
    them on the dispatch pool, so responses may arrive in any order)."""
    _eng, srv = server
    sock = _connect(srv.port)
    _startup(sock, version=4)
    sock.sendall(_query_envelope("SELECT * FROM system.local", 7)
                 + _query_envelope("SELECT * FROM system.local", 9))
    got = {}
    for _ in range(2):
        stream, op, _body = _read_envelope_legacy(sock)
        got[stream] = op
    assert got == {7: fr.OP_RESULT, 9: fr.OP_RESULT}
    sock.close()


# -------------------------------------------------------- admission -----

def test_permit_exhaustion_returns_overloaded(server):
    """With the permit cap pinched and execution slowed, concurrent
    requests past the cap are answered OVERLOADED immediately — and the
    in-flight high-water mark proves nothing ever queued past the cap."""
    eng, srv = server
    eng.settings.set("native_transport_max_concurrent_requests", 2)
    orig = srv.processor.process

    def slow_process(*a, **kw):
        time.sleep(0.25)
        return orig(*a, **kw)
    srv.processor.process = slow_process
    srv.permits.reset_high_water()
    results = []

    def one():
        s = Cluster("127.0.0.1", srv.port).connect()
        try:
            s.execute("SELECT * FROM system.local")
            results.append(("ok", None))
        except DriverError as e:
            results.append(("err", str(e)))
        finally:
            s.close()
    threads = [threading.Thread(target=one) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ok = [r for r in results if r[0] == "ok"]
    shed = [r for r in results if r[0] == "err" and "0x1001" in r[1]]
    assert ok, results
    assert shed, results
    assert srv.permits.high_water <= 2
    srv.processor.process = orig


def test_rate_limit_sheds_and_hot_reloads(server):
    """native_transport_rate_limit_ops sheds per-client with OVERLOADED
    (rate-limit message), counts into clientstats, and hot-reloads off
    — existing connections included (the settings listener reaches live
    limiters like the compaction throughput knob reaches the live
    compaction limiter)."""
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("SELECT * FROM system.local")          # unlimited: clean
    # rate=1: hot-enabling starts the bucket empty (refill 1 op/s), so
    # the shed assertion holds however slow the box is
    eng.settings.set("native_transport_rate_limit_ops", 1)
    shed = 0
    for _ in range(20):
        try:
            s.execute("SELECT * FROM system.local")
        except DriverError as e:
            assert "0x1001" in str(e) and "rate limit" in str(e).lower()
            shed += 1
    assert shed > 0
    from cassandra_tpu.tools.nodetool import clientstats
    stats = clientstats(eng)
    assert sum(c["rate_limited"] for c in stats) >= shed
    assert METRICS.counter("clients.rate_limited_requests") >= shed
    eng.settings.set("native_transport_rate_limit_ops", 0)
    for _ in range(5):
        s.execute("SELECT * FROM system.local")      # off again: clean
    s.close()


def test_overload_signal_from_write_stall():
    """REPEATED write stalls on the SERVER'S OWN engine trip the
    overload signal for STALL_WINDOW_S, then it clears (injected clock
    — no real sleeping). One stall is a routine threshold flush and
    must NOT shed; engine-scoped so a co-hosted node's stall can't shed
    this node's traffic."""
    class _Engine:
        write_stalls = 0
        commitlog = None
    eng = _Engine()
    clock = [1000.0]
    sig = OverloadSignals(eng, clock=lambda: clock[0])
    assert sig.reason() is None
    eng.write_stalls += 1                # ONE routine threshold flush
    clock[0] += 0.2                      # past the probe cache
    assert sig.reason() is None          # not overload
    eng.write_stalls += 1                # second stall inside the window
    clock[0] += 0.2
    assert "write_stall" in sig.reason()
    clock[0] += OverloadSignals.STALL_WINDOW_S + 0.1
    assert sig.reason() is None
    # a burst of stalls between two RECENT probes counts as repeated
    eng.write_stalls += 3
    clock[0] += 0.2
    assert "write_stall" in sig.reason()
    clock[0] += OverloadSignals.STALL_WINDOW_S + 0.1
    assert sig.reason() is None
    # ...but a multi-stall delta observed across a LONG probe gap does
    # not: probes only run on request arrival, so those stalls may be
    # minutes apart on an idle front door
    clock[0] += 600.0
    eng.write_stalls += 2
    clock[0] += 600.0
    assert sig.reason() is None
    # another engine's stalls are invisible to this signal
    other = OverloadSignals(object(), clock=lambda: clock[0])
    eng.write_stalls += 5
    clock[0] += 0.2
    assert other.reason() is None


def test_overload_signal_from_commitlog_backlog():
    class _CL:
        _waiting = OverloadSignals.PENDING_SYNCS_MAX + 1
        _retiring = []

    class _Engine:
        commitlog = _CL()
    sig = OverloadSignals(_Engine())
    assert "commitlog" in sig.reason()
    _CL._waiting = 0
    time.sleep(OverloadSignals.PROBE_INTERVAL_S + 0.05)
    assert sig.reason() is None


def test_permit_gate_cap_and_high_water():
    g = PermitGate(2)
    assert g.try_acquire() and g.try_acquire()
    assert not g.try_acquire()
    assert g.high_water == 2
    g.release()
    assert g.try_acquire()               # freed permit is reusable
    g.set_cap(0)                         # 0 = unlimited
    assert all(g.try_acquire() for _ in range(10))


# -------------------------------------------- prepared-statement LRU ----

def test_prepared_lru_eviction_unprepared_and_reprepare(server):
    """Bounding the registry: the LRU evicts, the evicted id answers
    the wire UNPREPARED error (0x2500, id echoed), re-PREPARE works,
    and prepared_statements.evicted counts."""
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("INSERT INTO kv (k, v) VALUES (1, 'one')")
    eng.settings.set("prepared_statements_cache_size", 3)
    evicted0 = METRICS.counter("prepared_statements.evicted")
    qid = s.prepare("SELECT v FROM kv WHERE k = 1")
    assert s.execute_prepared(qid).rows == [("one",)]
    for i in range(4):                   # push qid out of the LRU
        s.prepare(f"SELECT v FROM kv WHERE k = {10 + i}")
    assert METRICS.counter("prepared_statements.evicted") > evicted0
    with pytest.raises(DriverError, match="0x2500"):
        s.execute_prepared(qid)
    qid2 = s.prepare("SELECT v FROM kv WHERE k = 1")   # driver retry
    assert qid2 == qid                   # MD5 ids are stable
    assert s.execute_prepared(qid2).rows == [("one",)]
    s.close()


def test_unprepared_error_echoes_statement_id(server):
    """The UNPREPARED body carries [short bytes id] after the message
    so drivers know WHICH statement to re-prepare."""
    _eng, srv = server
    sock = _connect(srv.port)
    _startup(sock, version=4)
    bogus = b"\x01" * 16
    body = struct.pack(">H", len(bogus)) + bogus \
        + struct.pack(">H", 1) + b"\x00"
    sock.sendall(struct.pack(">BBhBI", 4, 0, 5, fr.OP_EXECUTE,
                             len(body)) + body)
    _stream, op, rbody = _read_envelope_legacy(sock)
    assert op == fr.OP_ERROR
    (code,) = struct.unpack_from(">i", rbody, 0)
    assert code == fr.ERR_UNPREPARED
    _msg, pos = fr._read_string(rbody, 4)
    (n,) = struct.unpack_from(">H", rbody, pos)
    assert rbody[pos + 2:pos + 2 + n] == bogus
    sock.close()


# --------------------------------------------------- lifecycle / close --

from cassandra_tpu.transport.server import server_thread_count


def test_close_is_idempotent_and_joins_threads(tmp_path):
    eng = StorageEngine(str(tmp_path / "d"), Schema(),
                        commitlog_sync="batch")
    srv = CQLServer(eng)
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("SELECT * FROM system.local")
    assert server_thread_count(srv.port)
    t0 = time.monotonic()
    srv.close()
    srv.close()                          # second close: no-op, no raise
    assert time.monotonic() - t0 < 5.5
    assert not server_thread_count(srv.port)
    # the open client observes the shutdown as EOF, not a hang
    with pytest.raises(Exception):
        s.execute("SELECT * FROM system.local")
    s.close()
    eng.close()


def test_fixed_thread_count_serving_256_connections(server):
    """The event-loop contract: 256 concurrent connections are all
    served by the same fixed thread set (no thread-per-connection)."""
    _eng, srv = server
    baseline = server_thread_count(srv.port)
    assert baseline == len(srv.event_loops) + len(srv.dispatcher.threads)
    socks = []
    try:
        for _ in range(256):
            sock = _connect(srv.port)
            _startup(sock, version=4)
            socks.append(sock)
        assert len(srv.clients) >= 256
        assert server_thread_count(srv.port) == baseline
        # and they all still work: a request on the last and first
        for sock in (socks[0], socks[-1]):
            sock.sendall(_query_envelope("SELECT * FROM system.local", 2))
            _stream, op, _b = _read_envelope_legacy(sock)
            assert op == fr.OP_RESULT
        assert server_thread_count(srv.port) == baseline
    finally:
        for sock in socks:
            sock.close()


def test_slow_event_push_consumer_disconnected_not_stalling(server,
                                                            monkeypatch):
    """A registered event client that stops reading is disconnected and
    counted once its push backlog passes the cap — the emitter and the
    event loop never block on it, and other clients keep being served."""
    from cassandra_tpu.transport import server as srvmod
    _eng, srv = server
    monkeypatch.setattr(srvmod, "EVENT_BACKLOG_CAP", 8192)
    sock = _connect(srv.port)
    _startup(sock, version=4)
    body = struct.pack(">H", 1) + fr._string("SCHEMA_CHANGE")
    sock.sendall(struct.pack(">BBhBI", 4, 0, 1, fr.OP_REGISTER,
                             len(body)) + body)
    _stream, op, _b = _read_envelope_legacy(sock)
    assert op == fr.OP_READY
    # shrink the kernel's appetite so the backlog builds fast
    info = next(iter(srv.clients.values()))
    try:
        info["conn"].sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_SNDBUF, 4096)
    except OSError:
        pass
    before = METRICS.counter("clients.slow_consumer_disconnects")
    healthy = Cluster("127.0.0.1", srv.port).connect()
    deadline = time.monotonic() + 20.0
    dropped = False
    ev = {"change": "CREATED", "target": "TABLE",
          "keyspace": "k" * 256, "name": "t" * 256}
    while time.monotonic() < deadline:
        for _ in range(200):             # flood, never reading
            srv._on_node_event("SCHEMA_CHANGE", ev)
        if METRICS.counter("clients.slow_consumer_disconnects") > before:
            dropped = True
            break
    assert dropped, "slow event consumer was never disconnected"
    # the event loop survived: a healthy client still gets answers
    assert healthy.execute("SELECT * FROM system.local").rows
    healthy.close()
    sock.close()


def test_response_backpressure_pauses_reads_not_disconnects(server,
                                                            monkeypatch):
    """Pipelining queries whose responses overrun the out-buffer cap
    engages BACKPRESSURE (reads pause until the client drains), not a
    slow-consumer disconnect — every response is delivered and the
    connection keeps working afterwards (the old sendall server's
    blocking semantics, kept on the event loop)."""
    from cassandra_tpu.client import serialize_params
    from cassandra_tpu.transport import server as srvmod
    monkeypatch.setattr(srvmod, "OUT_BUFFER_CAP", 1 << 20)   # 1 MiB
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("CREATE KEYSPACE bp WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE bp")
    s.execute("CREATE TABLE blobs (k int PRIMARY KEY, v blob)")
    t = eng.schema.get_table("bp", "blobs")
    wq = s.prepare("INSERT INTO blobs (k, v) VALUES (?, ?)")
    for i in range(8):
        s.execute_prepared(wq, serialize_params(
            t, ["k", "v"], [i, bytes(65536)]))   # ~512 KiB per SELECT
    before = METRICS.counter("clients.slow_consumer_disconnects")
    sock = _connect(srv.port)
    _startup(sock, version=4)
    n_q = 8                                      # ~4 MiB total >> cap
    sock.sendall(b"".join(
        _query_envelope("SELECT k, v FROM bp.blobs", i) for i in range(n_q)))
    got = set()
    for _ in range(n_q):
        stream, op, body = _read_envelope_legacy(sock)
        assert op == fr.OP_RESULT
        got.add(stream)
    assert got == set(range(n_q))
    # connection still alive and reads resumed after the drain
    sock.sendall(_query_envelope("SELECT k FROM bp.blobs WHERE k = 1", 99))
    stream, op, _b = _read_envelope_legacy(sock)
    assert (stream, op) == (99, fr.OP_RESULT)
    assert METRICS.counter("clients.slow_consumer_disconnects") == before
    sock.close()
    s.close()


def test_clientstats_reports_in_flight_and_rate_limited(server):
    eng, srv = server
    s = Cluster("127.0.0.1", srv.port).connect()
    s.execute("SELECT * FROM system.local")
    from cassandra_tpu.tools.nodetool import clientstats
    stats = clientstats(eng)
    assert stats
    for c in stats:
        assert {"in_flight", "rate_limited", "requests",
                "version", "address"} <= set(c)
    # in_flight drains EVENTUALLY: the worker decrements after the
    # response envelope is queued, so a client that already read its
    # response can observe 1 for an instant — poll to the invariant
    deadline = time.time() + 2.0
    while time.time() < deadline:
        stats = clientstats(eng)
        if all(c["in_flight"] == 0 for c in stats):
            break
        time.sleep(0.01)
    assert all(c["in_flight"] == 0 for c in stats)
    s.close()


def test_clients_vtable_has_admission_columns(tmp_path):
    """system_views.clients exposes in_flight + rate_limited (the
    ClientsTable role) through the same clientstats source."""
    from cassandra_tpu.cluster.node import LocalCluster
    cluster = LocalCluster(1, str(tmp_path), rf=1)
    srv = CQLServer(cluster.node(1))
    try:
        s = Cluster("127.0.0.1", srv.port).connect()
        rows = s.execute("SELECT address, in_flight, rate_limited "
                         "FROM system_views.clients")
        assert rows.rows
        s.close()
    finally:
        srv.close()
        cluster.shutdown()
