"""Divergent-replica read protections.

Short reads: replicas truncate at the pushed DataLimits before merging
(storage/cellbatch.py truncate_live_rows), so the coordinator carries
short-read protection — re-query with doubled limits on post-merge
shortfall (cluster/coordinator.py read_partition;
service/reads/ShortReadPartitionsProtection.java:40). The first test
pins the reference's canonical failure scenario end-to-end; deeper
SRP coverage lives in tests/test_datalimits.py.

Filtered reads: ReplicaFilteringProtection.java:66 — index candidates
are unioned over blockFor replicas per range and every candidate is
re-read at the read CL and re-checked, so stale matches are dropped and
matches a stale replica missed are found."""
import pytest

from cassandra_tpu.cluster.messaging import Verb
from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel


@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(2, str(tmp_path), rf=2)
    for n in c.nodes:
        n.proxy.timeout = 1.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 2}")
    s.execute("USE ks")
    yield c
    c.shutdown()


def test_limit_correct_under_divergent_tombstones(cluster):
    """The reference short-read scenario: one replica holds newer
    tombstones for the rows the other would contribute under LIMIT.
    A per-replica-LIMIT design returns too few (or stale) rows; the
    post-merge LIMIT here must return the true newest rows."""
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE t (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for c_ in range(1, 6):
        s.execute(f"INSERT INTO t (k, c, v) VALUES (1, {c_}, 'v{c_}')")
    # deletions reach only node1
    victim = cluster.nodes[1].endpoint
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    n1.default_cl = ConsistencyLevel.ONE
    for c_ in range(1, 4):
        s.execute(f"DELETE FROM t WHERE k = 1 AND c = {c_}")
    rule["remaining"] = 0
    # replica 2 still has rows 1..3 live; QUORUM LIMIT 2 must see
    # through them to the true survivors 4, 5
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT c, v FROM t WHERE k = 1 LIMIT 2").rows
    assert rows == [(4, "v4"), (5, "v5")]


def test_replica_filtering_protection_stale_match_dropped(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE u (k int PRIMARY KEY, v text)")
    s.execute("CREATE INDEX ON u (v)")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    s.execute("INSERT INTO u (k, v) VALUES (1, 'x')")
    s.execute("INSERT INTO u (k, v) VALUES (2, 'x')")
    # node2 misses the update of k=1 away from 'x'
    victim = cluster.nodes[1].endpoint
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    n1.default_cl = ConsistencyLevel.ONE
    s.execute("UPDATE u SET v = 'y' WHERE k = 1")
    rule["remaining"] = 0
    n1.default_cl = ConsistencyLevel.QUORUM
    # node2's index still claims k=1 matches 'x' — the CL re-read must
    # surface v='y' and the re-check must drop the stale candidate
    rows = s.execute("SELECT k FROM u WHERE v = 'x'").rows
    assert rows == [(2,)]
    # and the new value is findable even though node2 never indexed it
    rows = s.execute("SELECT k FROM u WHERE v = 'y'").rows
    assert rows == [(1,)]


def test_index_candidates_cover_all_ranges(tmp_path):
    """RF=1 on 3 nodes: every row lives on exactly one node. Candidate
    discovery from the coordinator's local index alone would miss rows
    owned by the other two — the distributed union must find them all."""
    c = LocalCluster(3, str(tmp_path), rf=1)
    try:
        for n in c.nodes:
            n.proxy.timeout = 1.0
        s = c.session(1)
        s.execute("CREATE KEYSPACE r1 WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE r1")
        s.execute("CREATE TABLE w (k int PRIMARY KEY, v text)")
        s.execute("CREATE INDEX ON w (v)")
        c.node(1).default_cl = ConsistencyLevel.ONE
        for k in range(30):
            s.execute(f"INSERT INTO w (k, v) VALUES ({k}, 'tag')")
        rows = s.execute("SELECT k FROM w WHERE v = 'tag'").rows
        assert sorted(r[0] for r in rows) == list(range(30))
        # sanity: the data really is spread across nodes
        t = c.schema.get_table("r1", "w")
        holders = set()
        for k in range(30):
            pk = t.columns["k"].cql_type.serialize(k)
            for i, n in enumerate(c.nodes):
                b = n.engine.store("r1", "w").read_partition(pk)
                if b is not None and len(b):
                    holders.add(i)
        assert len(holders) > 1
    finally:
        c.shutdown()
