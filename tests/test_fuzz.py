"""Seeded fuzzing against the model checker (the harry role —
test/harry/.../QuiescentChecker.java). Any failure prints the seed and
op index that reproduce it; set CTPU_FUZZ_SEED to replay."""
import os
import time

import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.tools.harry import Model, OpGenerator, check_partition

SEED = int(os.environ.get("CTPU_FUZZ_SEED", "20260729"))
N_OPS = int(os.environ.get("CTPU_FUZZ_OPS", "10000"))

DDL = ("CREATE TABLE t (k int, c int, v text, w int, "
       "PRIMARY KEY (k, c))")


def _compact(node):
    from cassandra_tpu.compaction.task import CompactionTask
    cfs = node.engine.store("fz", "t")
    inputs = list(cfs.live_sstables())
    if len(inputs) >= 2:
        CompactionTask(cfs, inputs).execute()


def _mk_cluster(tmp_path, n, rf):
    c = LocalCluster(n, str(tmp_path), rf=rf)
    for nd in c.nodes:
        nd.proxy.timeout = 2.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE fz WITH replication = "
              f"{{'class': 'SimpleStrategy', 'replication_factor': {rf}}}")
    s.execute("USE fz")
    s.execute(DDL)
    return c, s


def test_fuzz_single_node(tmp_path):
    """10k seeded ops on one node with interleaved flush/compaction;
    every partition checked against the model every 500 ops and at the
    end. This certifies the write path + merge/reconcile + tombstone
    algebra end-to-end through CQL."""
    cluster, s = _mk_cluster(tmp_path, 1, 1)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.ONE
    gen = OpGenerator(SEED)
    model = Model()
    try:
        for op in gen:
            if op.index >= N_OPS:
                break
            if op.kind == "flush":
                node.engine.store("fz", "t").flush()
            elif op.kind == "compact":
                _compact(node)
            else:
                s.execute(op.cql("t"))
            model.apply(op)
            if (op.index + 1) % 500 == 0:
                for pk in range(gen.n_pks):
                    check_partition(s, model, "t", pk, SEED, op.index)
        node.engine.store("fz", "t").flush()
        _compact(node)
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED, N_OPS)
    finally:
        cluster.shutdown()


def test_fuzz_cluster_with_drops(tmp_path):
    """Seeded ops against a 3-node RF=3 cluster while one replica's
    MUTATION stream is periodically dropped; after hints replay, every
    replica-quorum read must match the model (quiescent checking with
    faults — the harry-under-simulator role)."""
    from cassandra_tpu.cluster.messaging import Verb
    cluster, s = _mk_cluster(tmp_path, 3, 3)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.QUORUM
    gen = OpGenerator(SEED + 1)
    model = Model()
    n_ops = min(N_OPS, 2000)
    dropping = None
    try:
        for op in gen:
            if op.index >= n_ops:
                break
            if op.index % 400 == 200:       # start dropping a victim
                victim = cluster.nodes[1 + (op.index // 400) % 2]
                dropping = cluster.filters.drop(
                    verb=Verb.MUTATION_REQ, to=victim.endpoint)
            if op.index % 400 == 399 and dropping is not None:
                dropping["remaining"] = 0
                dropping = None
            if op.kind == "flush":
                node.engine.store("fz", "t").flush()
            elif op.kind == "compact":
                _compact(node)
            else:
                s.execute(op.cql("t"))
            model.apply(op)
        if dropping is not None:
            dropping["remaining"] = 0
        # quiesce: hints must drain to every node
        deadline = time.time() + 30
        while time.time() < deadline:
            if not any(n.hints.has_hints(ep)
                       for n in cluster.nodes
                       for ep in cluster.ring.endpoints):
                break
            time.sleep(0.2)
        node.default_cl = ConsistencyLevel.ALL
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED + 1, n_ops)
        # and each node's LOCAL data alone serves the model: ONE with a
        # self-first replica ordering reads node i's own copy, so a
        # replica that hint-replay failed to converge is caught here
        for i in (1, 2, 3):
            si = cluster.session(i)
            si.keyspace = "fz"
            cluster.node(i).default_cl = ConsistencyLevel.ONE
            for pk in range(0, gen.n_pks, 3):
                check_partition(si, model, "t", pk, SEED + 1, n_ops)
    finally:
        cluster.shutdown()


def test_fuzz_device_engine_agrees(tmp_path):
    """The same seeded stream, compacted with the numpy spec engine vs
    recompacted state must serve identical reads (cheap cross-engine
    agreement on fuzz-shaped data; the bit-identity tests in
    test_merge_device.py do the exhaustive version)."""
    cluster, s = _mk_cluster(tmp_path, 1, 1)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.ONE
    gen = OpGenerator(SEED + 2)
    model = Model()
    try:
        for op in gen:
            if op.index >= 1500:
                break
            if op.kind == "flush":
                node.engine.store("fz", "t").flush()
            elif op.kind == "compact":
                from cassandra_tpu.compaction.task import CompactionTask
                cfs = node.engine.store("fz", "t")
                inputs = list(cfs.live_sstables())
                if len(inputs) >= 2:
                    CompactionTask(cfs, inputs, engine="numpy").execute()
            else:
                s.execute(op.cql("t"))
            model.apply(op)
        node.engine.store("fz", "t").flush()
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED + 2, 1500)
    finally:
        cluster.shutdown()
