"""Device program registry: compile/dispatch/execute accounting for
every jitted entry point (observability layer 6, device half).

The JAX programs (ops/merge.py, ops/device_write.py, parallel/mesh.py)
were a black box: a first call on a new operand shape pays XLA
compilation (seconds to minutes for big sorts), warm calls pay dispatch
+ device execution, and nothing recorded which was which. This module
is the accounting layer:

  record_dispatch(kernel, shape_key, s) -> bool
      timed around the jitted call itself. jit compiles synchronously
      inside the call, so the FIRST dispatch for a (kernel, shape_key)
      pair is the compile: it is recorded under compile_s/compiles and
      excluded from the warm dispatch_s average; returns True for it.
      Every later dispatch of the same shape is warm. `compiles` is
      therefore exactly the recompile count by operand shape — a
      workload churning shape buckets shows up as a climbing compile
      counter, and that is the signal the RETRACE SENTINEL reads: a
      program whose compiles cross `retrace_budget` publishes a
      `profile.retrace` diagnostic event (once per program, re-armed by
      reset()) and counts every further recompile in
      `profile.retraces`, so a shape-bucket regression is caught the
      tick it happens instead of as a mystery slowdown.
  record_execute(kernel, s)
      timed around blocking on the result (device wait).
  wrap(name, fn)
      the auto-instrumentation seam: returns `fn` with dispatch timing,
      an argument-derived shape key and best-effort XLA cost analysis
      folded in. Trace-safe — a call whose operands are tracers is
      inside an ENCLOSING program's trace, where wall timing is
      meaningless and the outer program's dispatch already owns the
      cost, so the wrapper passes straight through.
  add_phases({phase: seconds})
      folds a CompactionTask.profile (io_decode / merge / pack / device /
      gather / compress / io_write / seal) into the process aggregate.

Per-program shape keys are tracked in a bounded LRU (SHAPE_CAP): under
shape-bucket churn the set no longer grows without bound; an evicted
shape that reappears counts as a fresh compile, which mirrors what a
bounded compilation cache would do and only biases `compiles` upward in
exactly the churn regime the sentinel exists to flag. `shape_count`
(live tracked shapes) and `shape_evictions` are both exported.

Surfaces: snapshot() feeds the system_views.device_profile and
system_views.device_programs virtual tables, the `kernel_profile`
section of bench.py output and the `profile` section of flight-recorder
bundles.

Process-global (like the device itself); engine-scoped consumers read
through the vtable which serves this singleton — acceptable because the
accelerator is shared by every in-process node anyway.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

# patchable clock seam (the pipeline-ledger pattern): tests freeze it,
# production leaves time.perf_counter
CLOCK = time.perf_counter

# live shape keys tracked per program (LRU, satellite of PR 17): the
# old unbounded set leaked one entry per shape bucket forever
SHAPE_CAP = 256


def _shape_of(x):
    """Hashable shape signature of one operand tree: arrays collapse to
    (shape, dtype), containers recurse, everything else to its literal
    (static argnums) or type name."""
    shape = getattr(x, "shape", None)
    if shape is not None:
        return ("arr", tuple(shape), str(getattr(x, "dtype", "?")))
    if isinstance(x, dict):
        return ("dict",) + tuple(
            (k, _shape_of(v)) for k, v in sorted(x.items()))
    if isinstance(x, (tuple, list)):
        return ("seq",) + tuple(_shape_of(v) for v in x)
    if isinstance(x, (int, float, str, bool, type(None))):
        return ("lit", x)
    return ("obj", type(x).__name__)


def _has_tracer(x) -> bool:
    """True iff any leaf of the operand tree is a jax Tracer — i.e. the
    call is happening INSIDE an enclosing trace."""
    try:
        from jax.core import Tracer
    except Exception:
        return False

    def walk(v):
        if isinstance(v, Tracer):
            return True
        if isinstance(v, dict):
            return any(walk(i) for i in v.values())
        if isinstance(v, (tuple, list)):
            return any(walk(i) for i in v)
        return False

    return walk(x)


class DeviceProgramRegistry:
    def __init__(self, shape_cap: int = SHAPE_CAP):
        self._lock = threading.Lock()
        self._kernels: dict[str, dict] = {}
        self._phases: dict[str, float] = {}
        self.shape_cap = int(shape_cap)
        # <= 0 disables the sentinel; the mutable
        # profiler_retrace_budget knob lands here (engine wiring)
        self.retrace_budget = 0

    def set_retrace_budget(self, budget) -> None:
        """The `profiler_retrace_budget` knob landing (process-global
        like the registry itself: last writer wins across co-hosted
        engines, same as the shared device)."""
        self.retrace_budget = int(budget)

    def _kernel_locked(self, name: str) -> dict:
        k = self._kernels.get(name)
        if k is None:
            k = self._kernels[name] = {
                "calls": 0, "compiles": 0, "compile_s": 0.0,
                "dispatch_s": 0.0, "execute_s": 0.0,
                "shapes": OrderedDict(), "shape_evictions": 0,
                "retraces": 0, "sentinel_fired": False, "cost": None}
        return k

    def record_dispatch(self, kernel: str, shape_key,
                        seconds: float) -> bool:
        fire = compiles = retraces = None
        with self._lock:
            k = self._kernel_locked(kernel)
            k["calls"] += 1
            shapes = k["shapes"]
            if shape_key in shapes:
                shapes.move_to_end(shape_key)
                k["dispatch_s"] += seconds
                return False
            shapes[shape_key] = True
            if len(shapes) > self.shape_cap:
                shapes.popitem(last=False)
                k["shape_evictions"] += 1
            k["compiles"] += 1
            k["compile_s"] += seconds
            budget = self.retrace_budget
            if budget > 0 and k["compiles"] > budget:
                k["retraces"] += 1
                fire = not k["sentinel_fired"]
                k["sentinel_fired"] = True
                compiles, retraces = k["compiles"], k["retraces"]
        if retraces is not None:
            # metrics + event OUTSIDE the registry lock (publish takes
            # the bus lock; never nest foreign locks under ours)
            from .metrics import GLOBAL as METRICS
            METRICS.incr("profile.retraces")
            if fire:
                from . import diagnostics
                diagnostics.publish(
                    "profile.retrace", program=kernel,
                    compiles=compiles, budget=self.retrace_budget,
                    retraces=retraces)
        return True

    def record_execute(self, kernel: str, seconds: float) -> None:
        with self._lock:
            k = self._kernel_locked(kernel)
            k["execute_s"] += seconds

    # ------------------------------------------------- auto-instrument --

    def wrap(self, name: str, fn, cost: bool = True):
        """Instrument one jitted entry point (see module docstring).
        Safe on dual-use kernels that are both host entry points and
        bodies of larger programs: tracer operands pass straight
        through untimed."""
        registry = self

        def wrapped(*args, **kwargs):
            if _has_tracer(args) or _has_tracer(kwargs):
                return fn(*args, **kwargs)
            key = _shape_of(args) if not kwargs \
                else (_shape_of(args),
                      _shape_of(tuple(sorted(kwargs.items()))))
            t0 = CLOCK()
            out = fn(*args, **kwargs)
            if registry.record_dispatch(name, key, CLOCK() - t0) \
                    and cost:
                registry.maybe_record_cost(name, fn, args, kwargs)
            return out

        wrapped.__name__ = getattr(fn, "__name__", name)
        wrapped.__wrapped__ = fn
        return wrapped

    def maybe_record_cost(self, kernel: str, fn, args=(),
                          kwargs=None) -> None:
        """Best-effort XLA cost analysis for a program's most recently
        compiled shape. jit caches the executable, so lower().compile()
        right after a compiling dispatch is a cache hit, not a second
        compile; backends without the analysis (or older jax APIs)
        simply leave cost at None."""
        try:
            lowered = fn.lower(*args, **(kwargs or {}))
            cost = lowered.compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0))
            nbytes = float(cost.get("bytes accessed", 0.0))
        except Exception:
            return
        with self._lock:
            self._kernel_locked(kernel)["cost"] = {
                "flops": flops, "bytes_accessed": nbytes}

    # ---------------------------------------------------------- phases --

    def add_phases(self, profile: dict) -> None:
        with self._lock:
            for phase, seconds in profile.items():
                self._phases[phase] = self._phases.get(phase, 0.0) \
                    + float(seconds)

    def snapshot(self) -> dict:
        """{"kernels": {name: {calls, compiles, shapes, shape_count,
        shape_evictions, retraces, compile_s, dispatch_s, execute_s,
        cost_flops, cost_bytes}}, "phases": {name: seconds}}. `shapes`
        (== shape_count, the LIVE tracked-shape count) is kept for the
        pre-registry consumers."""
        with self._lock:
            kernels = {}
            for name, k in self._kernels.items():
                cost = k["cost"] or {}
                kernels[name] = {
                    "calls": k["calls"], "compiles": k["compiles"],
                    "shapes": len(k["shapes"]),
                    "shape_count": len(k["shapes"]),
                    "shape_evictions": k["shape_evictions"],
                    "retraces": k["retraces"],
                    "compile_s": round(k["compile_s"], 6),
                    "dispatch_s": round(k["dispatch_s"], 6),
                    "execute_s": round(k["execute_s"], 6),
                    "cost_flops": float(cost.get("flops", 0.0)),
                    "cost_bytes": float(cost.get("bytes_accessed",
                                                 0.0))}
            phases = {p: round(s, 6) for p, s in self._phases.items()}
        return {"kernels": kernels, "phases": phases}

    def reset(self) -> None:
        with self._lock:
            self._kernels.clear()
            self._phases.clear()


# pre-registry name: the original compile/dispatch/execute accountant,
# kept so existing imports and tests keep meaning the same object
KernelProfiler = DeviceProgramRegistry

GLOBAL = DeviceProgramRegistry()
