"""Guardrails catalog (db/guardrails/Guardrails.java): warn/fail
thresholds wired through the CQL paths that trip them."""
import pytest

from cassandra_tpu.storage.guardrails import (GuardrailViolation,
                                              Guardrails)


def test_threshold_ladder_and_disabled_sides():
    g = Guardrails(columns_per_table_warn=2, columns_per_table_fail=4)
    g.check_columns_per_table(2, "t")      # at warn: ok
    g.check_columns_per_table(3, "t")      # above warn: warns
    assert any("columns in t" in w for w in g.warnings)
    with pytest.raises(GuardrailViolation):
        g.check_columns_per_table(5, "t")
    # 0 disables a side
    g2 = Guardrails(page_size_warn=0, page_size_fail=0)
    g2.check_page_size(10 ** 9)


def test_catalog_breadth():
    g = Guardrails()
    checks = [m for m in dir(g) if m.startswith("check_")]
    assert len(checks) >= 15, checks


@pytest.fixture
def node(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    c = LocalCluster(1, str(tmp_path), rf=1)
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    yield c.nodes[0], s
    c.shutdown()


def test_ddl_guardrails_fire_through_cql(node):
    n, s = node
    gr = n.engine.guardrails
    gr.columns_per_table_fail = 3
    with pytest.raises(Exception, match="columns"):
        s.execute("CREATE TABLE wide (k int PRIMARY KEY, a int, b int, "
                  "c int, d int)")
    gr.columns_per_table_fail = 500
    gr.fields_per_udt_fail = 2
    with pytest.raises(Exception, match="UDT"):
        s.execute("CREATE TYPE big (f1 int, f2 int, f3 int)")
    gr.minimum_replication_factor_fail = 2
    with pytest.raises(Exception, match="replication factor"):
        s.execute("CREATE KEYSPACE low WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    gr.minimum_replication_factor_fail = 0


def test_drop_truncate_and_filtering_gates(node):
    n, s = node
    s.execute("CREATE TABLE t (k int PRIMARY KEY, v int)")
    s.execute("INSERT INTO t (k, v) VALUES (1, 2)")
    gr = n.engine.guardrails
    gr.drop_truncate_table_enabled = False
    with pytest.raises(Exception, match="TRUNCATE"):
        s.execute("TRUNCATE t")
    with pytest.raises(Exception, match="DROP"):
        s.execute("DROP TABLE t")
    gr.drop_truncate_table_enabled = True
    gr.allow_filtering_enabled = False
    with pytest.raises(Exception, match="ALLOW FILTERING"):
        s.execute("SELECT * FROM t WHERE v = 2 ALLOW FILTERING")
    gr.allow_filtering_enabled = True


def test_collection_item_guardrail_fires(node):
    n, s = node
    s.execute("CREATE TABLE cm (k int PRIMARY KEY, m map<text,int>)")
    n.engine.guardrails.items_per_collection_fail = 2
    with pytest.raises(Exception, match="items in collection"):
        s.execute("UPDATE cm SET m = {'a': 1, 'b': 2, 'c': 3} "
                  "WHERE k = 1")
    n.engine.guardrails.items_per_collection_fail = 0


def test_index_and_view_counts_fire(node):
    """The 2i / MV counters must see EXISTING objects (regression:
    both once counted 0 and could never trip)."""
    n, s = node
    gr = n.engine.guardrails
    s.execute("CREATE TABLE gx (k int PRIMARY KEY, a int, b int, c int)")
    gr.secondary_indexes_per_table_fail = 2
    s.execute("CREATE INDEX ON gx (a)")
    s.execute("CREATE INDEX ON gx (b)")
    with pytest.raises(Exception, match="secondary indexes"):
        s.execute("CREATE INDEX ON gx (c)")
    gr.secondary_indexes_per_table_fail = 10
    s.execute("CREATE TABLE gb (k int, c int, v int, "
              "PRIMARY KEY (k, c))")
    gr.materialized_views_per_table_fail = 1
    s.execute("CREATE MATERIALIZED VIEW mv1 AS SELECT * FROM gb "
              "WHERE k IS NOT NULL AND c IS NOT NULL "
              "PRIMARY KEY (c, k)")
    with pytest.raises(Exception, match="materialized views"):
        s.execute("CREATE MATERIALIZED VIEW mv2 AS SELECT * FROM gb "
                  "WHERE k IS NOT NULL AND c IS NOT NULL "
                  "PRIMARY KEY (c, k)")
    gr.materialized_views_per_table_fail = 10


def test_vector_dimension_guardrail_sees_parsed_types(node):
    n, s = node
    n.engine.guardrails.vector_dimensions_fail = 16
    with pytest.raises(Exception, match="vector dimensions"):
        s.execute("CREATE TABLE vec (k int PRIMARY KEY, "
                  "e vector<float, 32>)")
    n.engine.guardrails.vector_dimensions_fail = 8192
    s.execute("CREATE TABLE vec (k int PRIMARY KEY, "
              "e vector<float, 8>)")
