"""loop-blocking: nothing blocking may be reachable from the transport
event loop's selector callbacks, nor from code running under the gossip
lock.

The event-loop threads (`transport/server.py::_EventLoop.run`) own
every connection assigned to them: one blocking call stalls ALL of that
loop's clients (and a blocked gossip lock stalls liveness for the whole
node). Queries don't run on the loop — the bounded dispatch executor
does — so the loop-reachable closure must stay free of:

    os.fsync / os.fdatasync      durability waits
    time.sleep                   (and sim-patched module-attr sleeps)
    <x>.wait() / .wait_for()     condition/event/process waits
    <thread-ish>.join()          thread & pool joins
    <sock>.sendall()             blocking socket writes
    <queue>.get()                blocking queue takes (argless)

Roots:
  * `_EventLoop.run` in cassandra_tpu/transport/server.py — everything
    the selector thread runs inline.
  * every call made while holding the Gossiper lock
    (cassandra_tpu/cluster/gossip.py) — gossip handlers run on the
    messaging dispatch path and the lock guards liveness.

Reachability is the walker's name-resolution call graph: dynamic
callbacks escape it (that is the LockWitness's domain); unresolvable
calls make the check err quiet, not noisy.
"""
from __future__ import annotations

import ast
import re

from ..report import Violation

NAME = "loop-blocking"

SERVER_MOD = "cassandra_tpu.transport.server"
GOSSIP_MOD = "cassandra_tpu.cluster.gossip"

_THREADISH = re.compile(
    r"(thread|worker|loop|pool|proc|syncer|executor)", re.I)
_WAIT_ATTRS = {"wait", "wait_for"}
_FSYNC = {("os", "fsync"), ("os", "fdatasync")}


def _blocking(call_parts: tuple, call_node: ast.Call | None) -> str | None:
    """Why this dotted call is blocking, or None."""
    tail = call_parts[-1]
    if len(call_parts) >= 2 and (call_parts[-2], tail) in _FSYNC:
        return "fsync"
    if tail == "fsync" or tail == "fdatasync":
        return "fsync"
    if tail == "sleep" and (len(call_parts) == 1
                            or call_parts[-2] in ("time", "_time")):
        return "sleep"
    if tail in _WAIT_ATTRS and len(call_parts) >= 2:
        return "condition/event wait"
    if tail == "join" and len(call_parts) >= 2 \
            and _THREADISH.search(call_parts[-2]):
        return "thread join"
    if tail == "sendall":
        return "blocking socket sendall"
    if tail == "get" and len(call_parts) >= 2 \
            and "queue" in call_parts[-2].lower() \
            and call_node is not None and not call_node.args \
            and not call_node.keywords:
        return "blocking queue get"
    return None


def _blocking_sites(fn):
    """[(line, why, parts)] direct blocking calls in fn. Re-walks the
    AST for the argless-queue-get rule (CallSites don't carry args)."""
    node_by_line = {}
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Call):
            node_by_line.setdefault(n.lineno, n)
    out = []
    for cs in fn.calls:
        why = _blocking(cs.parts, node_by_line.get(cs.line))
        if why:
            out.append((cs.line, why, ".".join(cs.parts)))
    return out


def run(index) -> list[Violation]:
    out = []
    seen = set()

    def report(reach, ctx):
        for fn in reach:
            for line, why, dotted in _blocking_sites(fn):
                key = (fn.module.relpath, line)
                if key in seen:
                    continue
                seen.add(key)
                chain = " -> ".join(index.chain(reach, fn))
                out.append(Violation(
                    NAME, fn.module.relpath, line,
                    f"{why} (`{dotted}`) reachable from {ctx} via "
                    f"{chain}"))

    server = index.modules.get(SERVER_MOD)
    if server is not None:
        loop_cls = server.classes.get("_EventLoop")
        run_fn = loop_cls.methods.get("run") if loop_cls else None
        if run_fn is not None:
            report(index.reachable([run_fn]),
                   "the transport event loop")

    gossip = index.modules.get(GOSSIP_MOD)
    if gossip is not None:
        gossip_roots = []
        for ci in gossip.classes.values():
            for fn in ci.methods.values():
                for cs in fn.calls:
                    if not any(h.module == GOSSIP_MOD for h in cs.held):
                        continue
                    # the blocking primitive may BE the held call
                    why = _blocking(cs.parts, None)
                    if why:
                        key = (fn.module.relpath, cs.line)
                        if key not in seen:
                            seen.add(key)
                            out.append(Violation(
                                NAME, fn.module.relpath, cs.line,
                                f"{why} (`{'.'.join(cs.parts)}`) while "
                                f"holding the gossip lock in "
                                f"{fn.qualname}"))
                        continue
                    tgt = index.resolve_call(fn, cs.parts)
                    if tgt is not None:
                        gossip_roots.append(tgt)
        if gossip_roots:
            report(index.reachable(gossip_roots),
                   "code holding the gossip lock")
    return out
