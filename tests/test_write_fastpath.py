"""Write-path fast lane: group-commit commitlog, sharded memtable,
pipelined flush (docs/write-path.md; CTPU_WRITE_FASTPATH A/B).

Covers the ISSUE-4 satellite matrix: commitlog replay edge cases (torn
final record, compressed records around a segment rotation, group-commit
durability under simulated crash), sync-failure accounting (the loop
must survive and count, not die silently), sharded-memtable identity
(concurrent apply == serial apply bit-for-bit; reads across shard
boundaries), batched apply identity, pipelined-flush identity, and the
full A/B harness (scripts/check_writepath_ab.py)."""
import os
import shutil
import struct
import threading
import uuid

import numpy as np
import pytest

from cassandra_tpu.schema import Schema, make_table
from cassandra_tpu.storage import commitlog as cl_mod
from cassandra_tpu.storage.cellbatch import content_digest
from cassandra_tpu.storage.commitlog import CommitLog
from cassandra_tpu.storage.memtable import Memtable
from cassandra_tpu.storage.mutation import Mutation


@pytest.fixture(autouse=True)
def _fastpath_env():
    prev = os.environ.get("CTPU_WRITE_FASTPATH")
    yield
    if prev is None:
        os.environ.pop("CTPU_WRITE_FASTPATH", None)
    else:
        os.environ["CTPU_WRITE_FASTPATH"] = prev


TID = uuid.UUID("00000000-0000-0000-0000-00000000a51e")


def _mut(i: int, payload: bytes = b"v") -> Mutation:
    m = Mutation(TID, b"pk-%05d" % i)
    m.add(b"", 8, b"", payload, 1_000 + i)
    return m


def _table():
    return make_table("ks", "t", pk=["id"], ck=["c"],
                      cols={"id": "int", "c": "int", "v": "blob"})


# ------------------------------------------------------------ commitlog --


def test_group_commit_durability_survives_crash(tmp_path):
    """A mutation acked under sync_mode='group' must be on disk the
    moment add() returns: a directory copy taken right after the acks
    (what a crash leaves) replays every acked record."""
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    d = str(tmp_path / "cl")
    cl = CommitLog(d, sync_mode="group", group_window_ms=2.0)
    n = 24
    ts = [threading.Thread(target=cl.add, args=(_mut(i),))
          for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    crash = str(tmp_path / "crash")
    shutil.copytree(d, crash)     # simulated crash: no close()
    cl.close()
    replayed = CommitLog(crash, sync_mode="periodic")
    got = sorted(m.pk for _pos, m in replayed.replay())
    replayed.close()
    assert got == sorted(b"pk-%05d" % i for i in range(n))


def test_batch_leader_coalesces_fsyncs(tmp_path):
    """Concurrent writers under sync_mode='batch' + fast lane must pay
    FEWER fsyncs than mutations (the group-commit win itself)."""
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    cl = CommitLog(str(tmp_path / "cl"), sync_mode="batch")
    before = cl._sync_hist.count
    n = 64
    ts = [threading.Thread(
        target=lambda k: [cl.add(_mut(k * 8 + j)) for j in range(8)],
        args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    syncs = cl._sync_hist.count - before
    cl.close()
    assert syncs < n, f"no coalescing: {syncs} fsyncs for {n} mutations"
    assert sum(1 for _ in cl.replay()) == n


def test_torn_final_record_stops_replay(tmp_path):
    """A torn tail (crash mid-append) terminates replay of that segment
    without losing the intact prefix."""
    cl = CommitLog(str(tmp_path / "cl"), sync_mode="batch")
    for i in range(5):
        cl.add(_mut(i))
    cl.close()
    seg = cl._seg_path(cl.segment_ids()[-1])
    with open(seg, "ab") as f:
        # frame header promising 1000 bytes, then a short payload
        f.write(struct.pack("<II", 1000, 0xDEADBEEF) + b"short")
    got = list(CommitLog(str(tmp_path / "cl"),
                         sync_mode="periodic").replay())
    assert len(got) == 5
    assert [m.pk for _p, m in got] == [b"pk-%05d" % i for i in range(5)]


def test_corrupt_crc_tail_stops_replay(tmp_path):
    cl = CommitLog(str(tmp_path / "cl"), sync_mode="batch")
    for i in range(4):
        cl.add(_mut(i))
    cl.close()
    seg = cl._seg_path(cl.segment_ids()[-1])
    payload = b"x" * 10
    with open(seg, "ab") as f:
        f.write(struct.pack("<II", len(payload), 0x12345678) + payload)
    got = list(CommitLog(str(tmp_path / "cl"),
                         sync_mode="periodic").replay())
    assert len(got) == 4


def test_compressed_records_across_segment_rotation(tmp_path):
    """Compressed frames written right up against (and across) segment
    rotations replay bit-identically — rotation is now asynchronous
    (the retiring segment syncs off the write path), and the tail of
    segment k must be intact when k+1 opens."""
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    d = str(tmp_path / "cl")
    payload = b"abcdefgh" * 64            # compressible
    cl = CommitLog(d, sync_mode="batch", segment_size=2048,
                   compression="LZ4Compressor")
    n = 120
    for i in range(n):
        cl.add(_mut(i, payload))
    assert len(cl.segment_ids()) > 2      # really rotated
    cl.close()
    got = list(CommitLog(d, sync_mode="periodic",
                         compression="LZ4Compressor").replay())
    assert [m.pk for _p, m in got] == [b"pk-%05d" % i for i in range(n)]
    assert all(m.ops[0][3] == payload for _p, m in got)


def test_compressed_encrypted_rotation_replay(tmp_path):
    """Compress-then-encrypt segments across rotations (the reference's
    EncryptedSegment composition)."""
    pytest.importorskip("cryptography")
    from cassandra_tpu.storage import encryption as enc_mod
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    prev_ctx = enc_mod.get_context()
    enc_mod.set_context(enc_mod.EncryptionContext(str(tmp_path / "keys")))
    try:
        d = str(tmp_path / "cl")
        payload = b"secret--" * 32
        cl = CommitLog(d, sync_mode="batch", segment_size=2048,
                       compression="LZ4Compressor", encrypt=True)
        n = 24
        for i in range(n):
            cl.add(_mut(i, payload))
        assert len(cl.segment_ids()) > 2
        cl.close()
        got = list(CommitLog(d, sync_mode="periodic",
                             compression="LZ4Compressor",
                             encrypt=True).replay())
        assert [m.pk for _p, m in got] == [b"pk-%05d" % i
                                           for i in range(n)]
        assert all(m.ops[0][3] == payload for _p, m in got)
    finally:
        enc_mod.set_context(prev_ctx)


def test_sync_failure_counted_not_silent(tmp_path, monkeypatch):
    """Satellite fix: a failing fsync increments commitlog.sync_failures
    and the syncer loop SURVIVES — before, it swallowed the error and
    exited, silently disabling periodic sync forever."""
    cl = CommitLog(str(tmp_path / "cl"), sync_mode="periodic",
                   sync_period_ms=20)
    cl.add(_mut(0))
    real_fsync = os.fsync
    fails = {"n": 0}

    def flaky(fd):
        if fails["n"] < 2:
            fails["n"] += 1
            raise OSError(5, "injected EIO")
        return real_fsync(fd)

    monkeypatch.setattr(cl_mod.os, "fsync", flaky)
    import time
    deadline = time.time() + 5
    while cl._sync_failures < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert cl._sync_failures >= 2
    assert cl._syncer.is_alive()          # the loop did NOT die
    # next sync succeeds and clears the error
    deadline = time.time() + 5
    while cl._sync_error is not None and time.time() < deadline:
        time.sleep(0.02)
    assert cl._sync_error is None
    monkeypatch.setattr(cl_mod.os, "fsync", real_fsync)
    cl.close()
    assert cl.stats()["sync_failures"] >= 2


def test_retired_segment_requeued_on_sync_failure(tmp_path, monkeypatch):
    """A retired (rotated) segment whose fsync fails must go BACK on the
    retiring queue: the next successful cycle advancing the synced
    watermark past its positions would otherwise ack writers whose
    bytes were never fsynced."""
    cl = CommitLog(str(tmp_path / "cl"), sync_mode="batch")
    cl.add(_mut(0))
    # hand-retire a real segment file (the rotation path's state)
    side = open(str(tmp_path / "cl" / "commitlog-99.log"), "ab")
    side.write(b"x")
    with cl._lock:
        cl._retiring.append((99, side))
    real_fsync = os.fsync
    state = {"fail": 1}

    def flaky(fd):
        if state["fail"] and fd == side.fileno():
            state["fail"] -= 1
            raise OSError(5, "injected EIO")
        return real_fsync(fd)

    monkeypatch.setattr(cl_mod.os, "fsync", flaky)
    with pytest.raises(OSError):
        cl.sync()
    with cl._lock:
        assert cl._retiring == [(99, side)]     # re-queued, not lost
    cl.sync()                                   # retries and completes
    with cl._lock:
        assert cl._retiring == []
    assert side.closed
    monkeypatch.setattr(cl_mod.os, "fsync", real_fsync)
    cl.close()


def test_commitlogstats_and_vtable(tmp_path):
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.tools import nodetool
    schema = Schema()
    schema.create_keyspace("ks")
    t = _table()
    schema.add_table(t)
    eng = StorageEngine(str(tmp_path / "d"), schema,
                        commitlog_sync="batch")
    vcol = t.columns["v"].column_id
    for i in range(8):
        m = Mutation(t.id, t.serialize_partition_key([i]))
        m.add(t.serialize_clustering([0]), vcol, b"", b"x", 100 + i)
        eng.apply(m)
    st = nodetool.commitlogstats(eng)
    assert st["enabled"] and st["segments"] >= 1
    assert st["sync_mode"] == "batch"
    assert st["oldest_dirty"] == 1
    assert st["waiting_on_commit_us"]["count"] > 0
    assert st["sync_latency_us"]["count"] > 0
    rows = eng.virtual_tables.get("system_views", "commitlog").rows()
    status = [r for r in rows if r["name"] == "<status>"]
    assert len(status) == 1 and status[0]["segments"] >= 1
    assert any(r["name"].startswith("commitlog-") for r in rows)
    eng.close()


# ------------------------------------------------------- sharded memtable --


def _fill_serial(t, muts):
    mem = Memtable(t, shards=1)
    for m in muts:
        mem.apply(m)
    return mem


def _mutations(t, n=400, seed=3):
    rng = np.random.default_rng(seed)
    vcol = t.columns["v"].column_id
    out = []
    for i in range(n):
        pk = t.serialize_partition_key([int(rng.integers(0, 37))])
        m = Mutation(t.id, pk)
        m.add(t.serialize_clustering([i]), vcol, b"",
              rng.integers(0, 256, 16, dtype=np.uint8).tobytes(),
              1_000_000 + i)
        out.append(m)
    return out


def test_concurrent_sharded_apply_bit_identical_to_serial():
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    t = _table()
    muts = _mutations(t)
    serial = _fill_serial(t, muts)
    sharded = Memtable(t, shards=8)
    ts = [threading.Thread(
        target=lambda sl: [sharded.apply(m) for m in sl],
        args=(muts[k::6],)) for k in range(6)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert sharded.shard_count == 8
    assert len(sharded) == len(serial)
    assert sharded.ops == serial.ops
    assert sharded.live_bytes == serial.live_bytes
    assert content_digest(sharded.flush_batch()) == \
        content_digest(serial.flush_batch())


def test_apply_batch_identical_and_reads_cross_shards():
    t = _table()
    muts = _mutations(t, n=300, seed=11)
    one_by_one = Memtable(t, shards=8)
    for m in muts:
        one_by_one.apply(m)
    batched = Memtable(t, shards=8)
    for i in range(0, len(muts), 64):
        batched.apply_batch(muts[i:i + 64])
    assert content_digest(batched.flush_batch()) == \
        content_digest(one_by_one.flush_batch())
    # point reads / contains across every shard boundary
    serial = _fill_serial(t, muts)
    for k in range(37):
        pk = t.serialize_partition_key([k])
        a = batched.read_partition(pk)
        b = serial.read_partition(pk)
        assert batched.contains(pk) == serial.contains(pk)
        if a is None or b is None:
            assert a is None and b is None
        else:
            assert content_digest(a) == content_digest(b)
    # absent partition
    pk = t.serialize_partition_key([999])
    assert not batched.contains(pk)
    assert batched.read_partition(pk) is None


def test_shard_runs_concatenate_in_token_order():
    """flush_shards yields ascending-identity runs: the pipelined flush
    feeds them straight to the writer's ordering guard."""
    t = _table()
    mem = Memtable(t, shards=8)
    for m in _mutations(t, n=200, seed=7):
        mem.apply(m)
    runs = list(mem.flush_shards())
    assert sum(len(r) for r in runs) == len(mem)
    last = None
    for r in runs:
        first = r.lanes[0].astype(">u4").tobytes()
        if last is not None:
            assert first > last
        last = r.lanes[-1].astype(">u4").tobytes()


def test_flush_pipelined_identical_to_serial(tmp_path):
    from cassandra_tpu.storage.table import ColumnFamilyStore
    t = _table()
    muts = _mutations(t, n=500, seed=23)
    digs = {}
    for fp in ("0", "1"):
        os.environ["CTPU_WRITE_FASTPATH"] = fp
        cfs = ColumnFamilyStore(t, str(tmp_path / ("fp" + fp)),
                                commitlog=None)
        cfs.apply_batch(muts)
        reader = cfs.flush()
        assert reader is not None
        digs[fp] = content_digest(cfs.scan_all(now=0))
        segs = list(reader.scanner())
        assert sum(len(s) for s in segs) == reader.n_cells
        for s in cfs.live_sstables():
            s.close()
    assert digs["0"] == digs["1"]


def test_fastpath_off_single_shard():
    os.environ["CTPU_WRITE_FASTPATH"] = "0"
    t = _table()
    assert Memtable(t).shard_count == 1
    os.environ["CTPU_WRITE_FASTPATH"] = "1"
    assert Memtable(t).shard_count == 8


# ------------------------------------------------------------ A/B harness --


def test_writepath_ab_harness(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_writepath_ab",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts",
            "check_writepath_ab.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    diverged = mod.run_check(str(tmp_path))
    assert diverged == [], "\n".join(diverged)
