"""Timestamps and expiry semantics.

Reference semantics: write timestamps are microseconds since epoch
(cql3 'USING TIMESTAMP'); localDeletionTime is seconds since epoch
(db/DeletionTime.java, db/LivenessInfo.java); NO_TTL=0, NO_EXPIRY handled
via sentinel (db/LivenessInfo.java:36-50)."""
from __future__ import annotations

import threading
import time

NO_TIMESTAMP = -(1 << 63)          # LivenessInfo.NO_TIMESTAMP
NO_TTL = 0
NO_DELETION_TIME = 0x7FFFFFFF      # int max: "not deleted / never expires"
LIVE_DELETION = (NO_TIMESTAMP, NO_DELETION_TIME)

_last_micros = 0
_micros_lock = threading.Lock()


def now_micros() -> int:
    """Monotonic-per-process microsecond clock (ClientState.getTimestamp
    semantics: never returns the same value twice, even across threads)."""
    global _last_micros
    with _micros_lock:
        t = time.time_ns() // 1000
        if t <= _last_micros:
            t = _last_micros + 1
        _last_micros = t
        return t


def now_seconds() -> int:
    return int(time.time())
