"""Text -> typed python value for a column's CQL type.

The ONE conversion used everywhere a value arrives as a string with a
known column type: cqlsh COPY FROM csv cells (tools/copyutil.py),
nodetool getendpoints keys, and JSON map KEYS (JSON object keys are
always strings; cql3 Json.java parses them by the map's key type).
Reference counterpart: pylib/cqlshlib/copyutil.py converters (scalars).
"""
from __future__ import annotations

import datetime
import uuid


def parse_text_value(text: str, cql_type):
    if text == "":
        return None
    name = type(cql_type).__name__
    if name in ("Int32Type", "LongType", "SmallIntType", "TinyIntType",
                "IntegerType", "CounterColumnType"):
        return int(text)
    if name in ("FloatType", "DoubleType", "DecimalType"):
        return float(text)
    if name == "BooleanType":
        return text.strip().lower() in ("true", "1", "yes")
    if name in ("UUIDType", "TimeUUIDType"):
        return uuid.UUID(text)
    if name == "BlobType":
        return bytes.fromhex(text[2:] if text.startswith("0x") else text)
    if name == "TimestampType":
        try:
            return datetime.datetime.fromisoformat(text)
        except ValueError:
            return datetime.datetime.fromtimestamp(
                float(text) / 1000.0, tz=datetime.timezone.utc)
    return text      # text/ascii/inet and unknowns pass through
