from .marshal import (  # noqa: F401
    CQLType, parse_type, TYPE_REGISTRY,
    AsciiType, TextType, BlobType, BooleanType, TinyIntType, SmallIntType,
    Int32Type, LongType, CounterColumnType, FloatType, DoubleType,
    DecimalType, IntegerType, TimestampType, SimpleDateType, TimeType,
    UUIDType, TimeUUIDType, InetAddressType, DurationType, EmptyType,
    ListType, SetType, MapType, TupleType, UserType, VectorType,
)
