"""Commitlog: segmented durable WAL with CRC-framed records and replay.

Reference counterpart: db/commitlog/CommitLog.java:300 (add),
CommitLogSegment, AbstractCommitLogSegmentManager (segment rotation,
per-table dirty tracking), CommitLogReplayer (boot replay). Sync
strategies: 'periodic' (buffered, background fsync every N ms) and 'batch'
(fsync before ack) — conf/cassandra.yaml commitlog_sync options.

Record frame: [u32 length][u32 crc32-of-payload][payload]. A zero length
or short read terminates replay of a segment (torn tail after crash).
"""
from __future__ import annotations

import os
import re
import struct
import threading
import zlib

from ..utils import fsutil
from .mutation import Mutation

_SEG_RE = re.compile(r"^commitlog-(\d+)\.log$")


class CommitLogPosition(tuple):
    """(segment_id, offset) — totally ordered."""
    def __new__(cls, segment_id: int, offset: int):
        return super().__new__(cls, (segment_id, offset))

    @property
    def segment_id(self):
        return self[0]

    @property
    def offset(self):
        return self[1]


class CommitLog:
    def __init__(self, directory: str, segment_size: int = 32 * 1024 * 1024,
                 sync_mode: str = "periodic", sync_period_ms: int = 1000):
        self.directory = directory
        self.segment_size = segment_size
        self.sync_mode = sync_mode
        self.sync_period_ms = sync_period_ms
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        existing = self.segment_ids()
        self._seg_id = (existing[-1] + 1) if existing else 1
        self._file = None
        self._open_segment()
        # dirty tracking: segment -> set of table ids with unflushed writes
        self._dirty: dict[int, set] = {}
        self._stop = threading.Event()
        self._syncer = None
        if sync_mode == "periodic":
            self._syncer = threading.Thread(target=self._sync_loop,
                                            daemon=True)
            self._syncer.start()

    # ------------------------------------------------------------ segments

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.directory, f"commitlog-{seg_id}.log")

    def segment_ids(self) -> list[int]:
        out = []
        for fn in os.listdir(self.directory):
            m = _SEG_RE.match(fn)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _open_segment(self) -> None:
        if self._file:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
        self._file = open(self._seg_path(self._seg_id), "ab")
        # reserve the whole segment's blocks up front (KEEP_SIZE: st_size
        # stays at the append point so replay's EOF/torn-tail detection is
        # unaffected). The reference pre-creates fixed-size segments for
        # the same reason (CommitLogSegment); on this box extending
        # writes are ~75x slower than writes into reserved blocks.
        fsutil.preallocate_keep_size(
            self._file.fileno(), self._file.tell(),
            max(0, self.segment_size - self._file.tell()))

    # ----------------------------------------------------------------- add

    def add(self, mutation: Mutation) -> CommitLogPosition:
        """Append a mutation; returns its position. With sync_mode='batch'
        the record is durable when this returns (CommitLog.add:300)."""
        payload = mutation.serialize()
        frame = struct.pack("<II", len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._file.tell() + len(frame) > self.segment_size:
                self._seg_id += 1
                self._open_segment()
            pos = CommitLogPosition(self._seg_id, self._file.tell())
            self._file.write(frame)
            self._dirty.setdefault(self._seg_id, set()).add(mutation.table_id)
            if self.sync_mode == "batch":
                self._file.flush()
                os.fsync(self._file.fileno())
        return pos

    def sync(self) -> None:
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())

    def _sync_loop(self) -> None:
        while not self._stop.wait(self.sync_period_ms / 1000.0):
            try:
                self.sync()
            except (OSError, ValueError):
                return

    # -------------------------------------------------------------- replay

    def replay(self):
        """Yield (position, Mutation) for every intact record on disk
        (CommitLogReplayer semantics: stop a segment at the first torn
        record)."""
        for seg_id in self.segment_ids():
            path = self._seg_path(seg_id)
            with open(path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + 8 <= len(data):
                length, crc = struct.unpack_from("<II", data, pos)
                if length == 0 or pos + 8 + length > len(data):
                    break  # torn tail
                payload = data[pos + 8: pos + 8 + length]
                if zlib.crc32(payload) != crc:
                    break  # corrupt tail
                yield CommitLogPosition(seg_id, pos), \
                    Mutation.deserialize(payload)
                pos += 8 + length

    # ----------------------------------------------------- flush lifecycle

    def discard_completed(self, table_id, upto: CommitLogPosition) -> None:
        """Mark a table's writes flushed up to `upto`; delete segments no
        table dirties anymore (CommitLog.discardCompletedSegments)."""
        with self._lock:
            # a segment at/after the flush point may hold post-switch writes
            # for this table, so only older segments become clean
            for seg_id in list(self._dirty):
                if seg_id < upto.segment_id:
                    self._dirty[seg_id].discard(table_id)
                    if not self._dirty[seg_id] and seg_id != self._seg_id:
                        try:
                            os.remove(self._seg_path(seg_id))
                        except FileNotFoundError:
                            pass
                        del self._dirty[seg_id]

    def forget_table(self, table_id) -> None:
        """A dropped table's writes no longer pin segments."""
        with self._lock:
            for seg_id in list(self._dirty):
                self._dirty[seg_id].discard(table_id)
                if not self._dirty[seg_id] and seg_id != self._seg_id:
                    try:
                        os.remove(self._seg_path(seg_id))
                    except FileNotFoundError:
                        pass
                    del self._dirty[seg_id]

    def current_position(self) -> CommitLogPosition:
        with self._lock:
            return CommitLogPosition(self._seg_id, self._file.tell())

    def delete_segments_before(self, seg_id: int) -> None:
        for s in self.segment_ids():
            if s < seg_id:
                try:
                    os.remove(self._seg_path(s))
                except FileNotFoundError:
                    pass
                self._dirty.pop(s, None)

    def close(self) -> None:
        self._stop.set()
        if self._syncer:
            self._syncer.join(timeout=2)
        with self._lock:
            if self._file and not self._file.closed:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
