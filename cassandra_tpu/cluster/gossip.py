"""Gossip liveness: heartbeat rounds + phi-accrual failure detection.

Reference counterpart: gms/Gossiper.java:132 (1 Hz rounds, SYN/ACK digest
exchange), gms/FailureDetector.java:71 (phi accrual over heartbeat
inter-arrival times, convict threshold 8).
"""
from __future__ import annotations

import math
import random
import threading
from ..utils import lockwitness
import time
from dataclasses import dataclass, field

from ..service import diagnostics
from .messaging import MessagingService, Verb
from .ring import Endpoint

PHI_CONVICT_THRESHOLD = 8.0


@dataclass
class EndpointState:
    generation: int
    version: int = 0
    alive: bool = True
    arrival_intervals: list = field(default_factory=list)
    last_heartbeat: float = 0.0
    app_states: dict = field(default_factory=dict)  # status, tokens, ...
    # operator-asserted death (force_convict): only a GENERATION advance
    # (the node actually restarting) may resurrect, never version churn
    # relayed through third-party digests
    forced_down: bool = False


class FailureDetector:
    """Phi accrual: phi = -log10(P(no heartbeat for `elapsed`)) under an
    exponential model of observed inter-arrival times. Until enough
    intervals are observed, `default_mean` stands in so a peer that dies
    right after startup is still convicted."""

    WINDOW = 100

    def __init__(self, default_mean: float = 1.0):
        self._states: dict[Endpoint, EndpointState] = {}
        self.default_mean = default_mean
        # live conviction threshold: Node binds this to the mutable
        # phi_convict_threshold knob (DatabaseDescriptor
        # .setPhiConvictThreshold role); the module constant is only
        # the default
        self.threshold = PHI_CONVICT_THRESHOLD

    def report(self, ep: Endpoint, state: EndpointState,
               now: float) -> None:
        if state.last_heartbeat > 0:
            state.arrival_intervals.append(now - state.last_heartbeat)
            if len(state.arrival_intervals) > self.WINDOW:
                state.arrival_intervals.pop(0)
        state.last_heartbeat = now

    def phi(self, state: EndpointState, now: float) -> float:
        if state.last_heartbeat == 0:
            return 0.0
        if state.arrival_intervals:
            mean = sum(state.arrival_intervals) / \
                len(state.arrival_intervals)
            mean = max(mean, 1e-3)
        else:
            mean = self.default_mean
        elapsed = now - state.last_heartbeat
        return (elapsed / mean) / math.log(10)

    def is_alive(self, state: EndpointState, now: float) -> bool:
        return self.phi(state, now) < self.threshold


class Gossiper:
    """Heartbeat exchange over the messaging service. interval configurable
    so tests can run accelerated rounds (the reference gossips at 1 Hz)."""

    def __init__(self, messaging: MessagingService, seeds: list[Endpoint],
                 interval: float = 1.0, clock=None):
        self.messaging = messaging
        self.ep = messaging.ep
        self.seeds = [s for s in seeds if s != self.ep]
        self.interval = interval
        # bound at CALL time through the module attribute, never as a
        # default argument: the simulator patches `time` on this module,
        # and a def-time `clock=time.monotonic` default would capture
        # the REAL clock before the patch (ctpulint clock-discipline)
        self.clock = clock if clock is not None else time.monotonic
        self.detector = FailureDetector(default_mean=max(interval * 3, 0.1))
        self.states: dict[Endpoint, EndpointState] = {
            self.ep: EndpointState(generation=int(time.time()))}
        self._lock = lockwitness.make_lock("gossip.state")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # per-instance RNG for peer selection: the deterministic
        # simulator seeds it per node; sharing the module RNG would let
        # any other thread's draws perturb a simulation's replay
        self.rng = random.Random()
        self.on_alive = None    # callbacks for hint replay etc.
        self.on_dead = None
        # called with (ep, app_states) when a peer's versioned state
        # advances — schema-epoch anti-entropy etc. Invoked OUTSIDE the
        # gossip lock; must not block (dispatch thread).
        self.on_app_state = None
        messaging.register_handler(Verb.GOSSIP_SYN, self._handle_syn)
        messaging.register_handler(Verb.GOSSIP_ACK, self._handle_ack_msg)

    # ----------------------------------------------------------- protocol

    def _digest(self) -> dict:
        with self._lock:
            me = self.states[self.ep]
            me.version += 1
            return {ep.name: (ep, st.generation, st.version,
                              dict(st.app_states))
                    for ep, st in self.states.items()}

    def _merge(self, digest: dict) -> None:
        now = self.clock()
        advanced = []
        with self._lock:
            for name, (ep, gen, ver, apps) in digest.items():
                if ep == self.ep:
                    continue
                st = self.states.get(ep)
                if st is None:
                    st = EndpointState(generation=gen, version=ver,
                                       app_states=apps)
                    self.states[ep] = st
                    self.detector.report(ep, st, now)
                    advanced.append((ep, dict(st.app_states)))
                elif (gen, ver) > (st.generation, st.version):
                    gen_advance = gen > st.generation
                    st.generation, st.version = gen, ver
                    st.app_states.update(apps)
                    self.detector.report(ep, st, now)
                    advanced.append((ep, dict(st.app_states)))
                    if not st.alive and (not st.forced_down or gen_advance):
                        st.alive = True
                        st.forced_down = False
                        diagnostics.publish("gossip.status",
                                            endpoint=ep.name,
                                            alive=True,
                                            source=self.ep.name)
                        if self.on_alive:
                            self.on_alive(ep)
        if self.on_app_state:
            for ep, apps in advanced:
                self.on_app_state(ep, apps)

    def _handle_syn(self, msg):
        self._merge(msg.payload)
        return Verb.GOSSIP_ACK, self._digest()

    def _handle_ack_msg(self, msg):
        self._merge(msg.payload)
        return None

    # ------------------------------------------------------------- rounds

    def round(self) -> None:
        """One gossip round: SYN a random live peer + maybe a seed, then
        re-evaluate liveness (GossipTask semantics)."""
        digest = self._digest()
        with self._lock:
            peers = [e for e in self.states if e != self.ep]
        targets = []
        if peers:
            targets.append(self.rng.choice(peers))
        if self.seeds and (not targets or self.rng.random() < 0.3):
            targets.append(self.rng.choice(self.seeds))
        for t in set(targets):
            self.messaging.send_with_callback(
                Verb.GOSSIP_SYN, digest, t,
                on_response=lambda m: self._merge(m.payload),
                timeout=self.interval * 2)
        self._check_liveness()

    def _check_liveness(self) -> None:
        now = self.clock()
        with self._lock:
            for ep, st in self.states.items():
                if ep == self.ep:
                    continue
                alive = self.detector.is_alive(st, now)
                if st.alive and not alive:
                    st.alive = False
                    diagnostics.publish("gossip.status",
                                        endpoint=ep.name, alive=False,
                                        source=self.ep.name)
                    if self.on_dead:
                        self.on_dead(ep)
                elif not st.alive and alive and not st.forced_down:
                    st.alive = True
                    diagnostics.publish("gossip.status",
                                        endpoint=ep.name, alive=True,
                                        source=self.ep.name)
                    if self.on_alive:
                        self.on_alive(ep)

    def live_endpoints(self) -> list[Endpoint]:
        with self._lock:
            return [ep for ep, st in self.states.items() if st.alive]

    def is_alive(self, ep: Endpoint) -> bool:
        with self._lock:
            st = self.states.get(ep)
            return bool(st and st.alive)

    def is_running(self) -> bool:
        return not self._stop.is_set() and self._thread is not None \
            and self._thread.is_alive()

    def force_convict(self, ep: Endpoint, generation: int | None = None,
                      version: int | None = None) -> None:
        """Operator-asserted death (nodetool assassinate / the replace
        flow's precondition). The state keeps its known (generation,
        version) so silent gossip digests can't resurrect it — only the
        node actually speaking again (a generation/version advance)
        does; last_heartbeat is pushed far past so phi stays convicted."""
        with self._lock:
            st = self.states.get(ep)
            if st is None:
                st = EndpointState(generation=generation or 0,
                                   version=version or 0)
                self.states[ep] = st
            st.alive = False
            st.forced_down = True
            st.arrival_intervals.clear()
            st.last_heartbeat = self.clock() - 1e9
        diagnostics.publish("gossip.status", endpoint=ep.name,
                            alive=False, forced=True,
                            source=self.ep.name)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        # restartable: nodetool enablegossip after disablegossip
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"gossip-{self.ep.name}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.round()
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
