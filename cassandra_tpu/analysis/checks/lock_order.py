"""lock-order: the static lock-acquisition graph must be acyclic.

An edge A→B means "some code path acquires B while holding A": either
syntactic nesting (`with a: ... with b:`) or a call made under A to a
function whose transitive lock closure contains B (the call-graph
approximation in walker.py). A cycle is a potential deadlock — two
threads entering the cycle from different edges can each hold the lock
the other wants.

Lock identity is the declaration site (`module:Class.attr`), merging
instances; the runtime LockWitness (utils/lockwitness.py) covers the
dynamic orders this pass cannot see (callbacks, engine-scoped
registries).

Suppression: a `ctpulint: allow` comment for lock-order on an inner
acquisition line (or the line above) removes the edges CREATED at that
line before cycle detection — so the reason documents why that nesting
is ordered safely.
"""
from __future__ import annotations

from ..report import Violation

NAME = "lock-order"


def _edges(index, suppressed_sites):
    """{(A, B): (relpath, line, via)} — first site wins (stable
    reporting); edges born at an allowlisted site are dropped."""
    closure = index.lock_closure()
    edges: dict = {}

    def add(a, b, rel, line, via):
        if a == b:
            return
        site = suppressed_sites.get((rel, line)) \
            or suppressed_sites.get((rel, line - 1))
        if site is not None:
            site.used = True
            return
        edges.setdefault((a, b), (rel, line, via))

    for fn in index.all_functions():
        rel = fn.module.relpath
        for lid, line, held in fn.acquisitions:
            for h in held:
                add(h, lid, rel, line, f"nested in {fn.qualname}")
        for cs in fn.calls:
            if not cs.held:
                continue
            tgt = index.resolve_call(fn, cs.parts)
            if tgt is None:
                continue
            for inner in closure.get(tgt, ()):
                for h in cs.held:
                    add(h, inner, rel, cs.line,
                        f"{fn.qualname} calls {tgt.qualname}")
    return edges


def _find_cycle(graph, start):
    """One simple cycle through `start`, as a node list, or None."""
    stack = [(start, [start])]
    seen = set()
    while stack:
        node, path = stack.pop()
        for nxt in graph.get(node, ()):
            if nxt == start:
                return path
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def run(index) -> list[Violation]:
    supp = {}
    for s in index.suppressions():
        if s.check == NAME and s.reason:
            supp[(s.path, s.line)] = s
    edges = _edges(index, supp)
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    out = []
    reported = set()
    for node in sorted(graph, key=str):
        if node in reported:
            continue
        cyc = _find_cycle(graph, node)
        if cyc is None:
            continue
        reported.update(cyc)
        ring = cyc + [cyc[0]]
        legs = []
        anchor = None
        for a, b in zip(ring, ring[1:]):
            rel, line, via = edges[(a, b)]
            if anchor is None:
                anchor = (rel, line)
            legs.append(f"{a} -> {b} at {rel}:{line} ({via})")
        out.append(Violation(
            NAME, anchor[0], anchor[1],
            "lock-order cycle (potential deadlock): "
            + "; ".join(legs)))
    return out
