"""Auth (PasswordAuthenticator/authorizer role) + cqlsh shell."""
import io

import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.service.auth import AuthenticationError, UnauthorizedError


def test_auth_roles_and_permissions(tmp_path):
    eng = StorageEngine(str(tmp_path / "a"), Schema(), commitlog_sync="batch",
                        auth_enabled=True)
    with pytest.raises(ValueError):
        Session(eng)                      # anonymous rejected
    with pytest.raises(AuthenticationError):
        Session(eng, user="cassandra", password="wrong")
    root = Session(eng, user="cassandra", password="cassandra")
    root.execute("CREATE KEYSPACE ks WITH replication = "
                 "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    root.execute("USE ks")
    root.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    root.execute("CREATE ROLE reader WITH password = 'secret'")
    root.execute("GRANT SELECT ON KEYSPACE ks TO reader")
    rs = root.execute("LIST ROLES")
    assert ("reader", False, True) in rs.rows

    reader = Session(eng, user="reader", password="secret")
    reader.keyspace = "ks"
    reader.execute("SELECT * FROM kv")            # allowed
    with pytest.raises(UnauthorizedError):
        reader.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    root.execute("GRANT MODIFY ON KEYSPACE ks TO reader")
    reader.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    root.execute("REVOKE MODIFY ON KEYSPACE ks FROM reader")
    with pytest.raises(UnauthorizedError):
        reader.execute("INSERT INTO kv (k, v) VALUES (2, 'y')")
    # auth state persists across restart
    eng.close()
    eng2 = StorageEngine(str(tmp_path / "a"), Schema(),
                         commitlog_sync="batch", auth_enabled=True)
    r2 = Session(eng2, user="reader", password="secret")
    r2.keyspace = "ks"
    r2.execute("SELECT * FROM kv")
    with pytest.raises(UnauthorizedError):
        r2.execute("INSERT INTO kv (k, v) VALUES (3, 'z')")
    eng2.close()


def test_cqlsh_repl(tmp_path):
    from cassandra_tpu.tools import cqlsh
    eng = StorageEngine(str(tmp_path / "c"), Schema(), commitlog_sync="batch")
    s = Session(eng)
    stdin = io.StringIO("""CREATE KEYSPACE ks WITH replication = {'class': 'SimpleStrategy', 'replication_factor': 1};
USE ks;
CREATE TABLE kv (k int PRIMARY KEY, v text);
INSERT INTO kv (k, v) VALUES (1, 'hello');
SELECT * FROM kv;
DESCRIBE tables
DESCRIBE kv
TRACING ON
SELECT v FROM kv WHERE k = 1;
BOGUS STATEMENT;
EXIT
""")
    out = io.StringIO()
    cqlsh.repl(s, stdin=stdin, stdout=out)
    text = out.getvalue()
    assert "hello" in text
    assert "(1 rows)" in text
    assert "ks.kv" in text                       # DESCRIBE tables
    assert "CREATE TABLE ks.kv" in text          # DESCRIBE kv
    assert "Tracing session" in text             # TRACING ON output
    assert "ParseError" in text                  # bad statement reported
    eng.close()
