"""Row cache: merged-partition LRU shared by every table store.

Reference counterpart: cache/RowCacheKey.java + the row cache in
CacheService.java:160 — caches the MERGED partition at the replica so a
repeat point read skips the memtable+sstable collation entirely.

One process-global byte-bounded LRU (`RowCacheService`) holds every
table's entries keyed by `(store key, partition key)`; each
ColumnFamilyStore talks to it through a thin per-table `RowCache`
handle. The store key is the table's data directory — unique per store,
so in-process multi-node clusters can never serve each other's
partitions. Capacity comes from `row_cache_size_mib` (falling back to
`row_cache_size`, then a built-in default — see resolve_capacity);
tables opt in via `WITH caching = {'rows_per_partition': 'ALL'}`.

Invalidation (the tentpole read-fastpath contract): a write to the key
drops the entry and bumps the table's generation; flush and any
sstable-set change (compaction, scrub, cleanup, bulk load) clear the
whole table's entries — a cached merge must never outlive the sstable
generation it was computed from, so the fastpath's timestamp-skip
collation and the cache can be A/B'd against the naive path
bit-for-bit. Partitions holding TTL cells are never cached: their
liveness depends on the read clock.

The generation counter doubles as the put-race sentinel (the reference
row cache's sentinel protocol): a reader captures it BEFORE
snapshotting its sources and put() refuses the entry if it moved —
otherwise a read racing a write could re-cache its pre-write merge
AFTER the writer's invalidate and serve stale data forever.

Keys (never values) are persisted across restarts by
storage/saved_caches.py (AutoSavingCache role) alongside the key cache.
"""
from __future__ import annotations

import threading
from ..utils import lockwitness
from collections import OrderedDict

DEFAULT_CAPACITY = 64 << 20     # bytes; used until config wires a size


def resolve_capacity(settings) -> int:
    """Capacity in bytes under the documented precedence: an explicit
    `row_cache_size_mib` (>= 0; 0 disables) wins, else a non-zero legacy
    `row_cache_size` (bytes), else the built-in default."""
    mib = settings.get("row_cache_size_mib")
    if mib >= 0:
        return int(mib) << 20
    legacy = settings.get("row_cache_size")
    if legacy > 0:
        return int(legacy)
    return DEFAULT_CAPACITY


def _size_of(batch) -> int:
    return int(batch.lanes.nbytes + batch.ts.nbytes + batch.ldt.nbytes
               + batch.ttl.nbytes + batch.flags.nbytes + batch.off.nbytes
               + batch.val_start.nbytes + batch.payload.nbytes)


class RowCacheService:
    """The shared LRU. All mutation happens under one lock; per-table
    views (keys/len/clear) filter by store key."""

    def __init__(self, capacity_bytes: int = DEFAULT_CAPACITY):
        self.capacity = capacity_bytes
        self._lru: "OrderedDict[tuple, object]" = OrderedDict()
        self._sizes: dict = {}
        self._counts: dict = {}       # store key -> live entry count
        self._gens: dict = {}         # store key -> generation
        self._bytes = 0
        self._lock = lockwitness.make_lock("storage.row_cache")
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------- lookup

    def generation(self, tkey) -> int:
        with self._lock:
            return self._gens.get(tkey, 0)

    def get(self, tkey, pk: bytes):
        with self._lock:
            batch = self._lru.get((tkey, pk))
            if batch is None:
                self.misses += 1
                return None
            self._lru.move_to_end((tkey, pk))
            self.hits += 1
            return batch

    def put(self, tkey, pk: bytes, batch, read_generation: int,
            table_capacity: int | None = None) -> None:
        from .cellbatch import FLAG_EXPIRING
        if len(batch) and (batch.flags & FLAG_EXPIRING).any():
            return    # liveness depends on the read clock: never cache
        size = _size_of(batch)
        if size > self.capacity:
            return
        with self._lock:
            if self._gens.get(tkey, 0) != read_generation:
                return    # an invalidation raced this read: don't cache
            key = (tkey, pk)
            if key not in self._lru:
                self._counts[tkey] = self._counts.get(tkey, 0) + 1
            else:
                self._bytes -= self._sizes[key]
            self._lru[key] = batch
            self._sizes[key] = size
            self._bytes += size
            self._lru.move_to_end(key)
            while self._bytes > self.capacity and self._lru:
                self._evict_oldest_locked()
            if table_capacity is not None:
                while self._counts.get(tkey, 0) > table_capacity:
                    self._evict_oldest_of_locked(tkey)

    def _evict_oldest_locked(self) -> None:
        k, _ = self._lru.popitem(last=False)
        self._bytes -= self._sizes.pop(k)
        self._counts[k[0]] -= 1
        self.evictions += 1

    def _evict_oldest_of_locked(self, tkey) -> None:
        for k in self._lru:
            if k[0] == tkey:
                del self._lru[k]
                self._bytes -= self._sizes.pop(k)
                self._counts[tkey] -= 1
                self.evictions += 1
                return

    # -------------------------------------------------------- invalidate

    def invalidate(self, tkey, pk: bytes) -> None:
        with self._lock:
            self._gens[tkey] = self._gens.get(tkey, 0) + 1
            if self._lru.pop((tkey, pk), None) is not None:
                self._bytes -= self._sizes.pop((tkey, pk))
                self._counts[tkey] -= 1

    def clear_table(self, tkey) -> None:
        with self._lock:
            self._gens[tkey] = self._gens.get(tkey, 0) + 1
            dead = [k for k in self._lru if k[0] == tkey]
            for k in dead:
                del self._lru[k]
                self._bytes -= self._sizes.pop(k)
            self._counts[tkey] = 0

    def clear(self) -> None:
        """nodetool invalidaterowcache."""
        with self._lock:
            for tkey in self._gens:
                self._gens[tkey] += 1
            self._lru.clear()
            self._sizes.clear()
            self._counts.clear()
            self._bytes = 0

    # -------------------------------------------------------------- misc

    def set_capacity(self, capacity_bytes: int) -> None:
        with self._lock:
            self.capacity = int(capacity_bytes)
            while self._bytes > self.capacity and self._lru:
                self._evict_oldest_locked()

    def table_len(self, tkey) -> int:
        with self._lock:
            return self._counts.get(tkey, 0)

    def table_keys(self, tkey) -> list[bytes]:
        """LRU-ordered pks (oldest first) — AutoSavingCache snapshot."""
        with self._lock:
            return [k[1] for k in self._lru if k[0] == tkey]

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._lru), "bytes": self._bytes,
                    "capacity": self.capacity, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}


GLOBAL = RowCacheService()


class RowCache:
    """Per-table handle over the shared service (the surface the store,
    nodetool and saved_caches talk to). Counts its own hits/misses so
    per-table ratios survive alongside the service totals."""

    def __init__(self, tkey, capacity: int = 1024,
                 service: RowCacheService | None = None):
        self.tkey = tkey
        self.capacity = capacity          # per-table entry bound
        self.service = service or GLOBAL
        self.hits = 0
        self.misses = 0

    @property
    def generation(self) -> int:
        return self.service.generation(self.tkey)

    def __len__(self) -> int:
        return self.service.table_len(self.tkey)

    def keys(self) -> list[bytes]:
        return self.service.table_keys(self.tkey)

    def get(self, pk: bytes):
        batch = self.service.get(self.tkey, pk)
        if batch is None:
            self.misses += 1
        else:
            self.hits += 1
        return batch

    def put(self, pk: bytes, batch, read_generation: int) -> None:
        self.service.put(self.tkey, pk, batch, read_generation,
                         table_capacity=self.capacity)

    def invalidate(self, pk: bytes) -> None:
        self.service.invalidate(self.tkey, pk)

    def clear(self) -> None:
        self.service.clear_table(self.tkey)
