from .manager import IndexManager  # noqa: F401
