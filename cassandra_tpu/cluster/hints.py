"""Hinted handoff: mutations for unreachable replicas, stored locally and
replayed when the target comes back.

Reference counterpart: hints/ (HintsBuffer/HintsWriter — per-host
append-only files, HintsDispatchExecutor replay on recovery), entry via
StorageProxy.submitHint.
"""
from __future__ import annotations

import os
import struct
import threading
import zlib

from ..storage.mutation import Mutation
from .ring import Endpoint


class HintsService:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self.metrics = {"written": 0, "replayed": 0}
        # nodetool disablehandoff: new hints are dropped (the reference's
        # StorageProxy.shouldHint gate)
        self.enabled = True
        # nodetool disablehintsfordc: DCs whose targets get no new hints
        self.disabled_dcs: set[str] = set()

    def _path(self, target: Endpoint) -> str:
        return os.path.join(self.directory, f"hints-{target.name}.db")

    def store(self, target: Endpoint, mutation: Mutation,
              redelivery: bool = False) -> None:
        """redelivery=True marks a hint being RE-stored after a failed
        dispatch send — those bypass the disablehandoff gate (the gate
        stops NEW hints only; already-persisted hints must never be
        silently dropped mid-replay; `nodetool truncatehints` is the
        explicit delete)."""
        if not self.enabled and not redelivery:
            return
        payload = mutation.serialize()
        frame = struct.pack("<II", len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            with open(self._path(target), "ab") as f:
                f.write(frame)
            self.metrics["written"] += 1

    def has_hints(self, target: Endpoint) -> bool:
        p = self._path(target)
        return os.path.exists(p) and os.path.getsize(p) > 0

    def dispatch(self, target: Endpoint, send_fn) -> int:
        """Replay hints for a recovered target through send_fn(mutation);
        the file is removed once fully dispatched.

        A CRC-corrupt RECORD is skipped and replay continues with the
        remainder (its length header still frames the stream; only the
        payload is rotten) — one flipped bit must not drop every hint
        queued behind it. Structural corruption (zero/overrunning
        length) makes the rest of the stream unframeable: replay stops
        there. Both count hints.corrupt_records."""
        from ..service.metrics import GLOBAL
        from ..utils import faultfs
        p = self._path(target)
        with self._lock:
            if not os.path.exists(p):
                return 0
            faultfs.check("hints.read", p)
            with open(p, "rb") as f:
                data = f.read()
            if faultfs.GLOBAL.active:
                data = faultfs.GLOBAL.on_read("hints.read", p, data)
            n = 0
            pos = 0
            while pos + 8 <= len(data):
                length, crc = struct.unpack_from("<II", data, pos)
                if length == 0 or pos + 8 + length > len(data):
                    GLOBAL.incr("hints.corrupt_records")
                    break
                payload = data[pos + 8: pos + 8 + length]
                pos += 8 + length
                if zlib.crc32(payload) != crc:
                    GLOBAL.incr("hints.corrupt_records")
                    continue
                send_fn(Mutation.deserialize(payload))
                n += 1
            os.remove(p)
            self.metrics["replayed"] += n
            return n

    def truncate(self, endpoint_name: str | None = None) -> int:
        """Delete persisted hint files (all, or one target's) under the
        service lock — `nodetool truncatehints` must not race a
        concurrent store()/dispatch() holding a file open (reference
        HintsService.deleteAllHints serializes through the catalog).
        Returns the number of files removed."""
        n = 0
        with self._lock:
            for fn in list(os.listdir(self.directory)):
                if not fn.startswith("hints-") or not fn.endswith(".db"):
                    continue
                if endpoint_name and fn != f"hints-{endpoint_name}.db":
                    continue
                os.remove(os.path.join(self.directory, fn))
                n += 1
        return n
