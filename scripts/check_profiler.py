#!/usr/bin/env python
"""CI check (tier-2): the continuous profiler — wall-clock sampler +
device-program registry (docs/observability.md layer 6).

Leg 1 (zero-cost-off + knob lifecycle): an engine with the profiler
knob at its default (off) must leave NO `wall-profiler` thread in the
process; flipping `profiler_enabled` live must start it, flipping it
back must park it, and `engine.close()` must withdraw the engine's
demand (the sampler is process-global — the demand pattern, same as
the diagnostic bus).

Leg 2 (flamegraph round-trip): a profiled session over a known
workload — one spinning thread, one parked on an Event — must produce
a collapsed-stack dump whose `parse_collapsed` totals equal the
session's split() (same aggregate, two encodings), classify the
spinner on-CPU and the parked thread blocked, and surface the same
stacks through `system_views.profiles` and `nodetool profiler dump`.

Leg 3 (retrace sentinel under forced shape churn): with
`profiler_retrace_budget` set low and the diagnostic bus on, a device
program dispatched across more distinct operand shapes than the budget
must increment `profile.retraces` per recompile past the budget,
publish exactly ONE `profile.retrace` diagnostic event for the
program (the sentinel is once-per-program until reset), expose the
count through `system_views.device_programs`, and land a `profile`
section in an on-demand flight-recorder bundle naming the program.

Exit 0 = clean; exit 1 prints each violation.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _build(base_dir: str, overrides: dict):
    from cassandra_tpu.config import Config, Settings
    from cassandra_tpu.schema import Schema, make_table
    from cassandra_tpu.storage.engine import StorageEngine
    schema = Schema()
    schema.create_keyspace("prof")
    t = make_table("prof", "t", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "text"})
    schema.add_table(t)
    settings = Settings(Config.load(overrides))
    return StorageEngine(base_dir, schema, commitlog_sync="periodic",
                         settings=settings), t


def _wall_threads() -> list:
    return [th for th in threading.enumerate()
            if th.name == "wall-profiler"]


def _await(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def check_lifecycle(base_dir: str) -> list[str]:
    from cassandra_tpu.service import sampler
    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    eng, _t = _build(os.path.join(base_dir, "n1"), {})
    try:
        # default off: zero cost means ZERO threads, not an idle one
        need(not sampler.GLOBAL.running,
             "sampler running with profiler_enabled at default (off)")
        need(not _wall_threads(),
             "wall-profiler thread exists with the knob off")
        eng.settings.set("profiler_interval", "10ms")
        eng.settings.set("profiler_enabled", True)
        need(_await(lambda: sampler.GLOBAL.running),
             "profiler_enabled=true did not start the sampler")
        before = sampler.GLOBAL.samples
        need(_await(lambda: sampler.GLOBAL.samples > before, 3.0),
             "running sampler ring is not accruing samples")
        eng.settings.set("profiler_enabled", False)
        need(_await(lambda: not sampler.GLOBAL.running),
             "profiler_enabled=false did not park the sampler")
        need(_await(lambda: not _wall_threads()),
             "wall-profiler thread survived the knob going off")
        # close() must withdraw demand even if the operator forgot
        eng.settings.set("profiler_enabled", True)
        need(_await(lambda: sampler.GLOBAL.running),
             "re-enable did not restart the sampler")
    finally:
        eng.close()
    need(_await(lambda: not sampler.GLOBAL.running),
         "engine.close() did not withdraw the sampler demand")
    return errs


def check_flamegraph(base_dir: str) -> list[str]:
    from cassandra_tpu.service import sampler
    from cassandra_tpu.tools import nodetool
    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    eng, _t = _build(os.path.join(base_dir, "n2"), {})
    stop = threading.Event()

    def _spin():
        x = 0
        while not stop.is_set():
            x = (x * 1103515245 + 12345) % (1 << 31)

    def _park():
        stop.wait(30.0)

    spinner = threading.Thread(target=_spin, name="gate-spin",
                               daemon=True)
    parked = threading.Thread(target=_park, name="gate-park",
                              daemon=True)
    try:
        eng.settings.set("profiler_interval", "5ms")
        out = nodetool.profiler(eng, "start")
        sid = out["session"]
        need(sampler.GLOBAL.running,
             "a live session did not start the sampler thread "
             "(sessions must work with the knob off)")
        spinner.start()
        parked.start()
        _await(lambda: sampler.GLOBAL.split(sid)["ticks"] >= 40,
               timeout_s=15.0)

        # vtable while the session is live: target = the session id
        vt = eng.virtual_tables.get("system_views", "profiles")
        vrows = [r for r in vt.rows() if r["target"] == sid]
        need(vrows, "system_views.profiles has no rows for the "
             "live session")

        split = nodetool.profiler(eng, "stop", session=sid)
        stop.set()
        dump = nodetool.profiler(eng, "dump", session=sid,
                                 limit=100_000)
        need(dump["target"] == sid, "dump targeted the wrong agg")

        # the round-trip: collapsed text -> parse -> same totals as
        # the structured split (one aggregate, two encodings)
        parsed = sampler.parse_collapsed(dump["flamegraph"])
        need(parsed["cpu"] == split["cpu"]
             and parsed["blocked"] == split["blocked"]
             and parsed["stacks"] == split["stacks"],
             f"flamegraph does not round-trip: parsed {parsed} vs "
             f"split cpu={split['cpu']} blocked={split['blocked']} "
             f"stacks={split['stacks']}")
        need(split["ticks"] >= 30,
             f"session collected only {split['ticks']} ticks")

        # classification: the spinner burns CPU, the parked thread
        # waits in threading.Event.wait -> blocked. DOMINANT state,
        # not exclusive: a thread's first ticks can land in the
        # threading.py bootstrap (-> blocked) before its target runs.
        counts: dict[tuple, int] = {}
        for line in dump["flamegraph"]:
            stack, _, n = line.rpartition(" ")
            state, tname = stack.split(";")[:2]
            key = (tname, state)
            counts[key] = counts.get(key, 0) + int(n)
        need(counts.get(("gate-spin", "cpu"), 0)
             > counts.get(("gate-spin", "blocked"), 0),
             f"spinner thread not dominantly on-CPU: {counts}")
        need(counts.get(("gate-park", "blocked"), 0)
             > counts.get(("gate-park", "cpu"), 0),
             f"parked thread not dominantly blocked: {counts}")
    finally:
        stop.set()
        eng.close()
    return errs


def check_sentinel(base_dir: str) -> list[str]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from cassandra_tpu.service import diagnostics, profiling
    from cassandra_tpu.tools import nodetool
    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    diagnostics.GLOBAL.clear()
    eng, _t = _build(os.path.join(base_dir, "n3"),
                     {"diagnostic_events_enabled": True,
                      "profiler_retrace_budget": 2})
    try:
        profiling.GLOBAL.reset()   # fresh kernels, budget stays 2
        probe = profiling.GLOBAL.wrap(
            "check.churn", jax.jit(lambda x: jnp.sum(x) + 1))
        churn = 7   # distinct shapes; budget 2 -> 5 past-budget traces
        for n in range(1, churn + 1):
            probe(np.zeros(n, dtype=np.float32))

        snap = profiling.GLOBAL.snapshot()["kernels"].get(
            "check.churn", {})
        need(snap.get("compiles") == churn,
             f"expected {churn} compiles, got {snap.get('compiles')}")
        need(snap.get("retraces") == churn - 2,
             f"expected {churn - 2} retraces past the budget, got "
             f"{snap.get('retraces')}")

        evs = [e.to_dict()
               for e in diagnostics.GLOBAL.events("profile.retrace")]
        need(len(evs) == 1,
             f"sentinel published {len(evs)} profile.retrace events "
             "(must be exactly one per program until reset)")
        if evs:
            need(evs[0].get("program") == "check.churn"
                 and evs[0].get("budget") == 2,
                 f"sentinel event fields wrong: {evs[0]}")

        vt = eng.virtual_tables.get("system_views", "device_programs")
        rows = {r["name"]: r for r in vt.rows()}
        need("check.churn" in rows
             and rows["check.churn"]["retraces"] == churn - 2,
             "system_views.device_programs does not carry the "
             "retrace count")

        out = nodetool.flightrecorder(eng)
        with open(out["bundle"]) as f:
            bundle = json.load(f)
        prof = bundle.get("profile", {})
        need(prof.get("retrace_budget") == 2,
             "bundle profile section lacks the retrace budget")
        need(prof.get("device_programs", {})
             .get("check.churn", {}).get("retraces") == churn - 2,
             "bundle profile section does not name the churning "
             "program")
    finally:
        eng.close()
        profiling.GLOBAL.reset()
        diagnostics.GLOBAL.reset()
    return errs


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    errs = []
    with tempfile.TemporaryDirectory() as d:
        errs += check_lifecycle(os.path.join(d, "lifecycle"))
        errs += check_flamegraph(os.path.join(d, "flame"))
        errs += check_sentinel(os.path.join(d, "sentinel"))
    if errs:
        print("check_profiler: FAIL", file=sys.stderr)
        for e in errs:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print("check_profiler: zero-cost-off, flamegraph round-trip and "
          "retrace sentinel OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
