"""User-defined functions/aggregates with the sandboxed expression
language (cql3/functions/UDFunction + UDAggregate roles)."""
import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.cql.functions import FunctionError, compile_expression
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine


@pytest.fixture
def tmp_data(tmp_path):
    return str(tmp_path / "data")


@pytest.fixture
def engine(tmp_data):
    eng = StorageEngine(tmp_data, Schema(), commitlog_sync="batch")
    yield eng
    eng.close()


@pytest.fixture
def session(engine):
    s = Session(engine)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE t (k int PRIMARY KEY, a int, b int)")
    for i in range(5):
        s.execute(f"INSERT INTO t (k, a, b) VALUES ({i}, {i}, {i * 10})")
    return s


def test_scalar_udf(session):
    session.execute("CREATE FUNCTION plus2 (x int) RETURNS int "
                    "LANGUAGE expr AS 'x + 2'")
    rs = session.execute("SELECT plus2(a) FROM t WHERE k = 3")
    assert rs.rows == [(5,)]
    session.execute("CREATE FUNCTION addab (x int, y int) RETURNS int "
                    "LANGUAGE expr AS 'x + y'")
    rs = session.execute("SELECT addab(a, b) FROM t WHERE k = 2")
    assert rs.rows == [(22,)]


def test_udf_null_propagates(session):
    session.execute("INSERT INTO t (k) VALUES (9)")
    session.execute("CREATE FUNCTION neg (x int) RETURNS int "
                    "LANGUAGE expr AS '-x'")
    rs = session.execute("SELECT neg(a) FROM t WHERE k = 9")
    assert rs.rows == [(None,)]


def test_uda(session):
    session.execute("CREATE FUNCTION acc (st int, x int) RETURNS int "
                    "LANGUAGE expr AS 'st + x * x'")
    session.execute("CREATE AGGREGATE sumsq (int) SFUNC acc STYPE int "
                    "INITCOND 0")
    rs = session.execute("SELECT sumsq(a) FROM t")
    assert rs.rows == [(sum(i * i for i in range(5)),)]


def test_sandbox_rejects_escapes(session):
    for body in ("__import__('os')", "x.__class__", "open('/etc/passwd')",
                 "[i for i in (1,2)]", "lambda: 1", "x[0]"):
        with pytest.raises(Exception):
            session.execute(
                f"CREATE OR REPLACE FUNCTION evil (x int) RETURNS int "
                f"LANGUAGE expr AS '{body}'")


def test_compile_expression_directly():
    f = compile_expression("max(x, y) * 2", ["x", "y"])
    assert f([3, 7]) == 14
    with pytest.raises(FunctionError):
        compile_expression("().__class__", ["x"])


def test_udf_persists_across_restart(tmp_data, engine, session):
    session.execute("CREATE FUNCTION twice (x int) RETURNS int "
                    "LANGUAGE expr AS 'x * 2'")
    engine.close()
    eng2 = StorageEngine(tmp_data, Schema(), commitlog_sync="batch")
    try:
        s2 = Session(eng2)
        s2.keyspace = "ks"
        assert s2.execute("SELECT twice(b) FROM t WHERE k = 4").rows \
            == [(80,)]
        s2.execute("DROP FUNCTION twice")
        with pytest.raises(Exception, match="unknown function"):
            s2.execute("SELECT twice(b) FROM t WHERE k = 4")
    finally:
        eng2.close()


def test_udf_memory_amplification_capped():
    """A single op may not allocate unbounded memory: seq*int, nested
    mults, and concat are size-estimated BEFORE execution (round-2
    advisor finding — 'x * 10**9' on a string allocated gigabytes)."""
    f = compile_expression("x * 1000000000", ["x"])
    with pytest.raises(FunctionError):
        f(["abc"])
    # int path for the same body is fine
    assert f([2]) == 2_000_000_000
    # nested amplification is caught at the step that crosses the cap
    g = compile_expression("((x * 1000) * 1000) * 1000", ["x"])
    with pytest.raises(FunctionError):
        g(["abcdefgh"])
    # modest string repeat still works
    h = compile_expression("x * 3", ["x"])
    assert h(["ab"]) == "ababab"
    # concat is capped too
    c = compile_expression("concat(x, x)", ["x"])
    assert c(["ab"]) == "abab"
    with pytest.raises(FunctionError):
        big = "y" * 600_000
        c([big])


def test_udf_string_formatting_rejected():
    """printf-style '%' on strings pads to widths the operand sizes
    don't bound — rejected at evaluation."""
    f = compile_expression("x % y", ["x", "y"])
    with pytest.raises(FunctionError):
        f(["%0999999999d", 5])
    assert f([7, 3]) == 1


def test_udf_list_amplification_capped():
    """Row values hand UDFs real Python lists — list * int is capped
    like str * int, and '__binop__' is reserved at CREATE time."""
    f = compile_expression("x * 1000000", ["x"])
    with pytest.raises(FunctionError):
        f([[1, 2, 3]])
    with pytest.raises(FunctionError):
        compile_expression("__binop__ + 1", ["__binop__"])
