#!/usr/bin/env python
"""CI check: every metric name registered in the codebase follows the
documented scheme (docs/observability.md):

    group(.sub)*.name — dot-separated, >= 2 components, each component
    lowercase [a-z0-9_]+ (the first starting with a letter).

Scanned call sites: .incr("...") / .hist("...") / .timer("...") /
.counter("...") / .register_gauge("...") / .group("...") string literals
(plain and f-strings) under cassandra_tpu/, scripts/ and bench.py.
f-string placeholders ({...}) count as one valid component — dynamic
parts like `table.{ks}.{name}.writes` pass structurally; their runtime
values are the caller's contract.

Names passed to a *group* facade (cfs.latency.hist("read_latency")) are
single components: the group prefix supplies the rest.

Beyond structure, every dotted name's TOP-LEVEL group must be one of
the documented groups (KNOWN_GROUPS — the "Established groups" list in
docs/observability.md plus the mesh.* data-plane group from
docs/multichip.md): a typo'd or undocumented group fails the check, so
new groups land in the docs the same commit they land in code.

Exit 0 = clean; exit 1 prints each violating file:line and name.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# whole-file scan (\s* spans newlines): a literal on the line AFTER the
# open paren is still validated
CALL_RE = re.compile(
    r"\.(incr|hist|timer|counter|register_gauge|group)\(\s*f?([\"'])"
    r"(?P<name>[^\"']+)\2")

COMPONENT = r"[a-z][a-z0-9_]*"
ANY_COMPONENT = r"(?:[a-z0-9_]+|X)"      # X = collapsed f-placeholder
FULL_RE = re.compile(rf"^{COMPONENT}(\.{ANY_COMPONENT})+$")
PREFIX_RE = re.compile(rf"^{COMPONENT}(\.{ANY_COMPONENT})*$")
SINGLE_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# the documented top-level groups (docs/observability.md "Established
# groups" + the mesh.* group from docs/multichip.md)
KNOWN_GROUPS = {
    "client_requests", "clients", "commitlog", "compaction",
    "compress_pool", "cql", "flush", "hints", "mesh",
    "prepared_statements", "reads", "request", "storage", "system",
    "table", "verb",
}


def _collapse_placeholders(name: str) -> str:
    return re.sub(r"\{[^{}]*\}", "X", name)


def check_name(method: str, raw: str) -> bool:
    name = _collapse_placeholders(raw)
    if method == "group":
        # dotless prefixes are indistinguishable from re.Match.group()
        # captures — only dotted prefixes get the group check
        return (PREFIX_RE.match(name) is not None
                and ("." not in name or _known_group(name)))
    if "." in name:
        return (FULL_RE.match(name) is not None
                and _known_group(name))
    # dotless: a group-member name (one component) — the group facade
    # supplied (and already validated) the prefix
    return SINGLE_RE.match(name) is not None


def _known_group(name: str) -> bool:
    top = name.split(".", 1)[0]
    # an f-placeholder top group is the caller's contract, not ours
    return top == "X" or top in KNOWN_GROUPS


def scan(paths=None) -> list[tuple[str, int, str, str]]:
    """[(relpath, lineno, method, name)] violations."""
    if paths is None:
        paths = []
        self_py = os.path.abspath(__file__)
        for top in ("cassandra_tpu", "scripts"):
            for root, _dirs, files in os.walk(os.path.join(REPO, top)):
                paths += [p for f in files if f.endswith(".py")
                          and (p := os.path.join(root, f)) != self_py]
        paths.append(os.path.join(REPO, "bench.py"))
    bad = []
    for p in sorted(paths):
        with open(p, encoding="utf-8") as f:
            text = f.read()
        for m in CALL_RE.finditer(text):
            method, name = m.group(1), m.group("name")
            if not check_name(method, name):
                lineno = text.count("\n", 0, m.start()) + 1
                bad.append((os.path.relpath(p, REPO), lineno,
                            method, name))
    return bad


def main() -> int:
    bad = scan()
    if bad:
        print("metric names outside the documented group.sub.name "
              "scheme (docs/observability.md):", file=sys.stderr)
        for path, lineno, method, name in bad:
            print(f"  {path}:{lineno}  .{method}({name!r})",
                  file=sys.stderr)
        return 1
    print("metric names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
