"""Metrics registry: counters + latency histograms.

Reference counterpart: metrics/CassandraMetricsRegistry.java (Dropwizard)
with TableMetrics / ClientRequestMetrics / CompactionMetrics groups and
DecayingEstimatedHistogramReservoir latency tracking. Here: plain counters
and a fixed-bucket log-scale histogram (the reference's estimated histogram
is also log-bucketed).
"""
from __future__ import annotations

import math
import threading
import time


class LatencyHistogram:
    """Log-scale bucket histogram of microsecond latencies."""

    N_BUCKETS = 64

    def __init__(self):
        self.buckets = [0] * self.N_BUCKETS
        self.count = 0
        self.total_us = 0
        self._lock = threading.Lock()

    def update_us(self, us: float) -> None:
        b = min(int(math.log2(max(us, 1))), self.N_BUCKETS - 1)
        with self._lock:
            self.buckets[b] += 1
            self.count += 1
            self.total_us += us

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self.count:
                return 0.0
            target = self.count * p
            acc = 0
            for b, c in enumerate(self.buckets):
                acc += c
                if acc >= target:
                    return float(2 ** b)
            return float(2 ** (self.N_BUCKETS - 1))

    @property
    def mean_us(self) -> float:
        with self._lock:
            return self.total_us / self.count if self.count else 0.0


class Timer:
    def __init__(self, hist: LatencyHistogram):
        self.hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.update_us((time.perf_counter() - self._t0) * 1e6)


class MetricsRegistry:
    """Grouped counters + histograms: metrics.group('table.ks.t').incr(..)"""

    def __init__(self):
        self._counters: dict[str, int] = {}
        self._hists: dict[str, LatencyHistogram] = {}
        self._lock = threading.Lock()

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        return self._counters.get(name, 0)

    def hist(self, name: str) -> LatencyHistogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram()
            return h

    def timer(self, name: str) -> Timer:
        return Timer(self.hist(name))

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            hists = list(self._hists.items())
        # histogram reads happen OUTSIDE the registry lock (each hist
        # has its own): keeps snapshot cheap under concurrent updates.
        # Live gauges are engine-scoped by design — see
        # CompactionManager.gauges() / the system_views.metrics vtable —
        # so in-process multi-node deployments never cross-report.
        for name, h in hists:
            out[f"{name}.count"] = h.count
            out[f"{name}.mean_us"] = round(h.mean_us, 1)
            out[f"{name}.p99_us"] = h.percentile(0.99)
        return out


GLOBAL = MetricsRegistry()
