"""cqlsh: interactive CQL shell.

Reference counterpart: bin/cqlsh.py + pylib/cqlshlib (9.5k LoC of
completion/formatting; this is the working core: statement loop, table
formatting, DESCRIBE, TRACING, SOURCE, EXIT).
"""
from __future__ import annotations

import argparse
import sys


def format_rows(rs, header: bool = True,
                footer_count: int | None = None) -> str:
    """Render a result table. For paged output: header only on the first
    page, footer only on the last (with the TRUE total row count)."""
    names = rs.column_names
    if not names:
        return ""
    rows = [[_fmt(v) for v in r] for r in rs.rows]
    widths = [max(len(n), *(len(r[i]) for r in rows)) if rows else len(n)
              for i, n in enumerate(names)]
    out = ""
    if header:
        head = " | ".join(n.ljust(w) for n, w in zip(names, widths))
        sep = "-+-".join("-" * w for w in widths)
        out = f" {head}\n-{sep}-"
    body = "\n".join(" | ".join(c.rjust(w) for c, w in zip(r, widths))
                     for r in rows)
    if body:
        out += ("\n " if out else " ") + body
    if footer_count is None:
        footer_count = len(rs.rows)
    if footer_count >= 0:
        out += f"\n\n({footer_count} rows)"
    return out


def _fmt(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bytes):
        return "0x" + v.hex()
    if isinstance(v, bool):
        return str(v)
    return str(v)


def describe(session, what: str) -> str:
    schema = session.processor.executor.schema
    what = what.strip().lower()
    if what in ("keyspaces", ""):
        return "\n".join(schema.keyspaces) or "(none)"
    if what == "tables":
        out = []
        for ks in schema.keyspaces.values():
            for t in ks.tables:
                out.append(f"{ks.name}.{t}")
        return "\n".join(out) or "(none)"
    if what.startswith("table "):
        what = what[len("table "):]
    parts = what.strip().split(".")
    if len(parts) == 2:
        ksn, tn = parts
    else:
        ksn, tn = session.keyspace, parts[0]
    t = schema.get_table(ksn, tn)
    cols = []
    for c in t.partition_key_columns:
        cols.append(f"    {c.name} {c.cql_type!r}")
    for c in t.clustering_columns:
        cols.append(f"    {c.name} {c.cql_type!r}")
    for c in t.static_columns:
        cols.append(f"    {c.name} {c.cql_type!r} static")
    for c in t.regular_columns:
        cols.append(f"    {c.name} {c.cql_type!r}")
    pk = ", ".join(c.name for c in t.partition_key_columns)
    if len(t.partition_key_columns) > 1:
        pk = f"({pk})"
    key = ", ".join([pk] + [c.name for c in t.clustering_columns])
    return (f"CREATE TABLE {t.keyspace}.{t.name} (\n"
            + ",\n".join(cols)
            + f",\n    PRIMARY KEY ({key})\n)")


def repl(session, stdin=None, stdout=None):
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    tracing = False
    buf = ""
    prompt = "cqlsh> "

    def emit(s):
        print(s, file=stdout)

    emit("Connected to cassandra_tpu. Type EXIT to quit.")
    while True:
        try:
            stdout.write(prompt if not buf else "   ... ")
            stdout.flush()
            line = stdin.readline()
        except KeyboardInterrupt:
            buf = ""
            continue
        if not line:
            break
        stripped = line.strip()
        if not buf:
            low = stripped.lower().rstrip(";")
            if low in ("exit", "quit"):
                break
            if low.startswith("describe") or low.startswith("desc "):
                try:
                    emit(describe(session,
                                  stripped.rstrip(";").split(None, 1)[1]
                                  if " " in stripped else ""))
                except Exception as e:
                    emit(f"error: {e}")
                continue
            if low.startswith("copy "):
                from . import copyutil
                spec = copyutil.parse_copy(stripped)
                if spec is None:
                    emit("Bad COPY syntax: COPY <table> [(cols)] TO|FROM "
                         "'<file>' [WITH HEADER = true]")
                    continue
                try:
                    if spec["direction"] == "to":
                        n = copyutil.copy_to(session, spec["table"],
                                             spec["columns"], spec["path"],
                                             spec["header"])
                        emit(f"Exported {n} rows to {spec['path']}")
                    else:
                        n = copyutil.copy_from(
                            session, session.processor.executor.schema,
                            session.keyspace, spec["table"],
                            spec["columns"], spec["path"], spec["header"])
                        emit(f"Imported {n} rows from {spec['path']}")
                except Exception as e:
                    emit(f"{type(e).__name__}: {e}")
                continue
            if low == "tracing on":
                tracing = True
                emit("Tracing enabled")
                continue
            if low == "tracing off":
                tracing = False
                emit("Tracing disabled")
                continue
            if not stripped:
                continue
        buf += line
        is_batch = buf.strip().lower().startswith("begin")
        # statements end with ';'; BEGIN BATCH blocks span lines until
        # APPLY BATCH
        if not is_batch and ";" not in buf:
            continue
        if is_batch and "apply batch" not in buf.lower():
            continue
        stmt = buf
        buf = ""
        try:
            # SELECTs page like the reference cqlsh (default 5000 rows a
            # page) — a huge table never materializes client-side at once
            if stmt.strip().lower().startswith("select"):
                rs = session.execute(stmt, trace=tracing, fetch_size=5000)
                # one table across pages: header once, rows streamed,
                # one footer with the true total
                total = len(rs.rows)
                last = rs.paging_state is None
                out = format_rows(rs, header=True,
                                  footer_count=total if last else -1)
                if out:
                    emit(out)
                page = rs
                while page.paging_state is not None:
                    page = session.execute(stmt, fetch_size=5000,
                                           paging_state=page.paging_state)
                    total += len(page.rows)
                    last = page.paging_state is None
                    out = format_rows(page, header=False,
                                      footer_count=total if last else -1)
                    if out:
                        emit(out)
                # rs stays the FIRST page: its trace block prints below
            else:
                rs = session.execute(stmt, trace=tracing)
                out = format_rows(rs)
                if out:
                    emit(out)
            if tracing and hasattr(rs, "trace"):
                emit("\nTracing session: " + str(rs.trace.session_id))
                for us, src, activity in rs.trace.events:
                    emit(f"  {activity} [{src}] -- +{us} us")
        except Exception as e:
            emit(f"{type(e).__name__}: {e}")
    emit("")


def main(argv=None):
    p = argparse.ArgumentParser(prog="cqlsh")
    p.add_argument("--data", required=True)
    p.add_argument("-e", "--execute", help="run one statement and exit")
    p.add_argument("-f", "--file", help="run statements from a file")
    p.add_argument("-u", "--user", help="role name (auth-enabled dirs)")
    p.add_argument("-p", "--password", default="")
    args = p.parse_args(argv)

    from ..cql import Session
    from ..schema import Schema
    from ..storage.engine import StorageEngine
    import os as _os
    auth_on = _os.path.exists(_os.path.join(args.data, "system_auth.json"))
    engine = StorageEngine(args.data, Schema(), auth_enabled=auth_on)
    session = Session(engine, user=args.user, password=args.password)
    try:
        if args.execute:
            rs = session.execute(args.execute)
            out = format_rows(rs)
            if out:
                print(out)
        elif args.file:
            with open(args.file) as f:
                for stmt in f.read().split(";"):
                    if stmt.strip():
                        session.execute(stmt)
        else:
            repl(session)
    finally:
        engine.close()


if __name__ == "__main__":
    main()
