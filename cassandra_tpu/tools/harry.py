"""harry — seeded operation-stream fuzzer with a model checker.

Reference counterpart: test/harry (deterministic data generator +
QuiescentChecker: ops are generated reproducibly from a seed, applied to
the system under test AND to a pure model; reads are verified against
the model's computed expectation —
test/harry/main/org/apache/cassandra/harry/model/QuiescentChecker.java).

The model implements the full deletion algebra the storage engine must
honor: newest-timestamp-wins cells with the CASSANDRA-14592
equal-timestamp ranking (expiring-or-tombstone beats live, PURE
tombstone beats expiring, larger localDeletionTime, larger value
bytes), TTL expiry against a virtual clock (`advance` ops move it, so
expiry is deterministic and replayable from the seed), expiration-
overflow capping (db/ExpirationDateOverflowHandling.java), row liveness
(INSERT creates a row; UPDATE alone leaves it dependent on live cells),
static rows, multicell collections with complex deletions
(db/rows/ComplexColumnData), column/row/partition tombstones,
clustering range tombstones, and flush/compaction as visibility no-ops.
Any mismatch reports the seed + op index that reproduce it.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field

from ..utils.timeutil import NO_DELETION_TIME, expiration_time

# ---------------------------------------------------------------- ops --


@dataclass
class Op:
    index: int
    kind: str
    pk: int
    ck: int | None = None
    cols: dict | None = None       # col -> value for writes
    ts: int = 0
    ttl: int = 0                   # 0 = no TTL
    lo: int | None = None          # range delete bounds [lo, hi)
    hi: int | None = None
    col: str | None = None         # single-column delete
    key: str | None = None         # map element key
    val: int | None = None         # map element value
    items: dict | None = None      # map literal for overwrite/append
    seconds: int = 0               # virtual-clock advance
    cond: tuple | None = None      # LWT: (col, expected_value)

    def _using(self) -> str:
        u = f"USING TIMESTAMP {self.ts}"
        if self.ttl:
            u += f" AND TTL {self.ttl}"
        return u

    def cql(self, table: str) -> str | None:
        """The CQL statement for this op (None for flush/compact/advance)."""
        k = self.kind
        if k == "insert":
            v, w = self.cols["v"], self.cols["w"]
            return (f"INSERT INTO {table} (k, c, v, w) VALUES "
                    f"({self.pk}, {self.ck}, '{v}', {w}) {self._using()}")
        if k == "update":
            sets = ", ".join(
                f"{c} = " + (f"'{x}'" if c == "v" else str(x))
                for c, x in self.cols.items())
            return (f"UPDATE {table} {self._using()} "
                    f"SET {sets} WHERE k = {self.pk} AND c = {self.ck}")
        if k == "del_row":
            return (f"DELETE FROM {table} USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        if k == "del_col":
            return (f"DELETE {self.col} FROM {table} "
                    f"USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        if k == "del_part":
            return (f"DELETE FROM {table} USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk}")
        if k == "del_range":
            return (f"DELETE FROM {table} USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk} AND c >= {self.lo} "
                    f"AND c < {self.hi}")
        if k == "set_static":
            return (f"UPDATE {table} {self._using()} "
                    f"SET st = '{self.val}' WHERE k = {self.pk}")
        if k == "del_static":
            return (f"DELETE st FROM {table} USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk}")
        if k == "map_set":
            return (f"UPDATE {table} {self._using()} "
                    f"SET m['{self.key}'] = {self.val} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        if k == "map_del_elem":
            return (f"DELETE m['{self.key}'] FROM {table} "
                    f"USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        if k == "map_overwrite":
            lit = "{" + ", ".join(f"'{mk}': {mv}"
                                  for mk, mv in self.items.items()) + "}"
            return (f"UPDATE {table} {self._using()} SET m = {lit} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        if k == "map_append":
            lit = "{" + ", ".join(f"'{mk}': {mv}"
                                  for mk, mv in self.items.items()) + "}"
            return (f"UPDATE {table} {self._using()} SET m = m + {lit} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        if k == "del_map":
            return (f"DELETE m FROM {table} USING TIMESTAMP {self.ts} "
                    f"WHERE k = {self.pk} AND c = {self.ck}")
        return None


class OpGenerator:
    """Reproducible op stream from a seed (harry's generators role).
    Small key universe on purpose: collisions between writes, deletes
    and range tombstones are where reconcile bugs live. Timestamps
    collide on purpose too — the equal-ts ranking is a reconcile
    corner. TTLs are drawn against the VIRTUAL clock the `advance`
    ops move, so some cells expire mid-stream deterministically."""

    KINDS = [("insert", 26), ("update", 14), ("del_row", 8),
             ("del_col", 5), ("del_part", 2), ("del_range", 6),
             ("map_set", 8), ("map_del_elem", 3), ("map_overwrite", 3),
             ("map_append", 3), ("del_map", 2),
             ("set_static", 5), ("del_static", 2),
             ("advance", 5), ("flush", 9), ("compact", 4)]

    # TTL palette: 0 = none; short ones expire as the clock advances;
    # MAX_TTL exercises the expiration-overflow cap
    TTLS = (0, 0, 0, 0, 3, 8, 30, 86400, 20 * 365 * 24 * 3600)
    MAP_KEYS = ("a", "b", "cc")

    def __init__(self, seed: int, n_pks: int = 8, n_cks: int = 16,
                 features: bool = True):
        self.rng = random.Random(seed)
        self.seed = seed
        self.n_pks = n_pks
        self.n_cks = n_cks
        self._i = 0
        kinds = self.KINDS if features else [
            (k, w) for k, w in self.KINDS
            if not k.startswith(("map_", "set_static", "del_static"))
            and k not in ("del_map", "advance")]
        self._kinds = [k for k, w in kinds for _ in range(w)]

    def __iter__(self):
        return self

    def _ttl(self) -> int:
        return self.rng.choice(self.TTLS)

    def __next__(self) -> Op:
        rng = self.rng
        i = self._i
        self._i += 1
        kind = rng.choice(self._kinds)
        pk = rng.randrange(self.n_pks)
        # timestamps collide on purpose (same-ts tie-breaks are a
        # reconcile corner): draw from a window ~= op count
        ts = rng.randrange(1, max(2, self._i * 2))
        op = Op(i, kind, pk, ts=ts)
        if kind in ("insert", "update", "del_row", "del_col", "map_set",
                    "map_del_elem", "map_overwrite", "map_append",
                    "del_map"):
            op.ck = rng.randrange(self.n_cks)
        if kind == "insert":
            op.cols = {"v": f"s{self.seed}i{i}", "w": i}
            op.ttl = self._ttl()
        elif kind == "update":
            which = rng.randrange(3)
            op.cols = {}
            if which in (0, 2):
                op.cols["v"] = f"s{self.seed}u{i}"
            if which in (1, 2):
                op.cols["w"] = i
            op.ttl = self._ttl()
        elif kind == "del_col":
            op.col = rng.choice(["v", "w"])
        elif kind == "del_range":
            lo = rng.randrange(self.n_cks)
            op.lo, op.hi = lo, lo + rng.randrange(1, self.n_cks // 2)
        elif kind == "set_static":
            op.val = f"st{i}"
            op.ttl = self._ttl()
        elif kind == "map_set":
            op.key = rng.choice(self.MAP_KEYS)
            op.val = i
            op.ttl = self._ttl()
        elif kind == "map_del_elem":
            op.key = rng.choice(self.MAP_KEYS)
        elif kind in ("map_overwrite", "map_append"):
            nk = rng.randrange(1, len(self.MAP_KEYS) + 1)
            op.items = {mk: i * 10 + j for j, mk in
                        enumerate(rng.sample(self.MAP_KEYS, nk))}
            op.ttl = self._ttl()
        elif kind == "advance":
            op.seconds = rng.randrange(1, 12)
        return op


# -------------------------------------------------------------- model --


def _enc(col: str, value) -> bytes:
    """Serialized bytes of a value, as the engine compares them in
    equal-timestamp tie-breaks (text -> utf8, int -> 4-byte BE)."""
    if col in ("v", "st"):
        return str(value).encode()
    return int(value).to_bytes(4, "big", signed=True)


class _Cell:
    """(ts, value, ldt): value None = tombstone (pure, no ttl);
    ldt = NO_DELETION_TIME for non-expiring data, the delete's
    now-seconds for tombstones, the capped expiry for TTL'd cells."""
    __slots__ = ("ts", "value", "ldt", "enc")

    def __init__(self, ts, value, ldt, enc=b""):
        self.ts, self.value, self.ldt, self.enc = ts, value, ldt, enc

    @property
    def death(self) -> bool:
        return self.value is None

    def rank(self):
        """The engine's equal-ts ranking (CellBatch.sort_permutation,
        merge.cpp beats(), CASSANDRA-14592): ts, then eot, then PURE
        tombstone (model tombstones are always pure — no TTL), then
        ldt, then value bytes."""
        eot = self.death or self.ldt != NO_DELETION_TIME
        return (self.ts, eot, self.death, self.ldt, self.enc)

    def visible(self, shadow_ts: int, now: int) -> bool:
        return (not self.death) and self.ts > shadow_ts \
            and self.ldt > now


def _put(slot: dict, key, cell: _Cell) -> None:
    old = slot.get(key)
    if old is None or cell.rank() > old.rank():
        slot[key] = cell


def _data_cell(col, value, ts, ttl, now_s) -> _Cell:
    ldt = expiration_time(now_s, ttl) if ttl else NO_DELETION_TIME
    return _Cell(ts, value, ldt, _enc(col, value))


@dataclass
class _RowState:
    liveness: _Cell | None = None               # INSERT's row marker
    cells: dict = field(default_factory=dict)   # col -> _Cell
    row_del_ts: int = -1
    map_del_ts: int = -1                        # complex deletion of m
    map_elems: dict = field(default_factory=dict)   # key -> _Cell


class Model:
    """Pure-python oracle of CQL read results (QuiescentChecker model).
    apply()/reads take the VIRTUAL now (seconds) so TTL expiry is
    deterministic; the harness drives the engine with the same clock
    (utils/timeutil.CLOCK)."""

    COLS = ("v", "w")

    def __init__(self):
        self.parts: dict = {}
        # pk -> {"del_ts", "ranges", "rows", "statics"}

    def _part(self, pk):
        return self.parts.setdefault(
            pk, {"del_ts": -1, "ranges": [], "rows": {}, "statics": {}})

    def _row(self, pk, ck) -> _RowState:
        return self._part(pk)["rows"].setdefault(ck, _RowState())

    def apply(self, op: Op, now_s: int = 0) -> None:
        k = op.kind
        if k in ("flush", "compact", "advance"):
            return
        p = self._part(op.pk)
        if k == "insert":
            row = self._row(op.pk, op.ck)
            lv = _Cell(op.ts, b"", expiration_time(now_s, op.ttl)
                       if op.ttl else NO_DELETION_TIME)
            if row.liveness is None or lv.rank() > row.liveness.rank():
                row.liveness = lv
            for c, val in op.cols.items():
                _put(row.cells, c, _data_cell(c, val, op.ts, op.ttl,
                                              now_s))
        elif k == "update":
            row = self._row(op.pk, op.ck)
            for c, val in op.cols.items():
                _put(row.cells, c, _data_cell(c, val, op.ts, op.ttl,
                                              now_s))
        elif k == "del_row":
            row = self._row(op.pk, op.ck)
            row.row_del_ts = max(row.row_del_ts, op.ts)
        elif k == "del_col":
            row = self._row(op.pk, op.ck)
            _put(row.cells, op.col, _Cell(op.ts, None, now_s))
        elif k == "del_part":
            p["del_ts"] = max(p["del_ts"], op.ts)
        elif k == "del_range":
            p["ranges"].append((op.lo, op.hi, op.ts))
        elif k == "set_static":
            _put(p["statics"], "st",
                 _data_cell("st", op.val, op.ts, op.ttl, now_s))
        elif k == "del_static":
            _put(p["statics"], "st", _Cell(op.ts, None, now_s))
        elif k == "map_set":
            row = self._row(op.pk, op.ck)
            _put(row.map_elems, op.key,
                 _data_cell("m", op.val, op.ts, op.ttl, now_s))
        elif k == "map_del_elem":
            row = self._row(op.pk, op.ck)
            _put(row.map_elems, op.key, _Cell(op.ts, None, now_s))
        elif k == "map_overwrite":
            # engine: complex deletion at ts-1, then element cells at ts
            # (cql/execution.py _add_cell_ops overwrite_collection)
            row = self._row(op.pk, op.ck)
            row.map_del_ts = max(row.map_del_ts, op.ts - 1)
            for mk, mv in op.items.items():
                _put(row.map_elems, mk,
                     _data_cell("m", mv, op.ts, op.ttl, now_s))
        elif k == "map_append":
            row = self._row(op.pk, op.ck)
            for mk, mv in op.items.items():
                _put(row.map_elems, mk,
                     _data_cell("m", mv, op.ts, op.ttl, now_s))
        elif k == "del_map":
            row = self._row(op.pk, op.ck)
            row.map_del_ts = max(row.map_del_ts, op.ts)

    # ------------------------------------------------------------ reads --

    def _eff_del(self, pk, ck) -> int:
        p = self.parts.get(pk)
        if p is None:
            return -1
        d = p["del_ts"]
        for lo, hi, ts in p["ranges"]:
            if lo <= ck < hi:
                d = max(d, ts)
        row = p["rows"].get(ck)
        if row is not None:
            d = max(d, row.row_del_ts)
        return d

    def static_value(self, pk, now: int):
        """Visible static value of the partition (shadowed only by the
        partition deletion — statics have no clustering, so range and
        row tombstones never cover them)."""
        p = self.parts.get(pk)
        if p is None:
            return None
        cell = p["statics"].get("st")
        if cell is not None and cell.visible(p["del_ts"], now):
            return cell.value
        return None

    def read_partition(self, pk, now: int = 0) -> dict:
        """ck -> {col: value} for visible rows (missing col = null;
        'm' maps to a dict of visible elements; 'st' joins the
        partition's static value onto every visible row)."""
        p = self.parts.get(pk)
        if p is None:
            return {}
        st = self.static_value(pk, now)
        out = {}
        for ck, row in p["rows"].items():
            d = self._eff_del(pk, ck)
            cols = {}
            for c, cell in row.cells.items():
                if cell.visible(d, now):
                    cols[c] = cell.value
            melems = {}
            for mk, cell in row.map_elems.items():
                if cell.visible(max(d, row.map_del_ts), now):
                    melems[mk] = cell.value
            if melems:
                cols["m"] = melems
            live = bool(cols) or (
                row.liveness is not None
                and row.liveness.ts > d and row.liveness.ldt > now)
            if live:
                if st is not None:
                    cols["st"] = st
                out[ck] = cols
        if not out and st is not None:
            # a partition whose only live content is its static row
            # still yields ONE row with null clusterings (reference
            # SelectStatement static semantics; engine matches)
            out[None] = {"st": st}
        return out


def check_partition(session, model: Model, table: str, pk: int,
                    seed: int, upto: int, now: int | None = None) -> None:
    """Compare a SELECT against the model (QuiescentChecker.validate)."""
    if now is None:
        from ..utils import timeutil
        now = timeutil.now_seconds()
    rows = session.execute(
        f"SELECT c, v, w, st, m FROM {table} WHERE k = {pk}").rows
    got = {}
    for c, v, w, st, m in rows:
        cols = {}
        if v is not None:
            cols["v"] = v
        if w is not None:
            cols["w"] = w
        if st is not None:
            cols["st"] = st
        if m:
            cols["m"] = dict(m)
        got[c] = cols
    expected = model.read_partition(pk, now)
    assert got == expected, (
        f"MISMATCH seed={seed} after op {upto} pk={pk}:\n"
        f"  engine: {got}\n  model:  {expected}\n"
        f"reproduce: CTPU_FUZZ_SEED={seed}")
