"""CQL type system: Python value <-> serialized bytes (native-protocol
binary formats) and serialized bytes -> byte-comparable encoding.

Reference: src/java/org/apache/cassandra/db/marshal/ (50 AbstractType
subclasses; serialization formats are the public native-protocol v5 binary
formats, doc/native_protocol_v5.spec section 6). Byte-comparable encodings
are our own order-preserving design (utils/bytecomp.py) — the device merge
kernel compares them as fixed-width unsigned lanes.

Each type provides:
  serialize(py) -> bytes          deserialize(bytes) -> py
  to_bytecomp(serialized) -> bytes    (order == type's comparison order)
  validate(serialized)            (raises on malformed input)
"""
from __future__ import annotations

import ipaddress
import socket
import struct
import uuid as uuid_mod
from datetime import date, datetime, timedelta, timezone
from decimal import Decimal

from ..utils import bytecomp
from ..utils import varint as vi

_EPOCH_DATE_BIAS = 1 << 31  # SimpleDateType: unsigned days with 2^31 = 1970-01-01


class CQLType:
    name: str = "?"
    is_counter = False
    is_collection = False
    is_multicell = False  # non-frozen collections/UDTs

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def to_bytecomp(self, data: bytes) -> bytes:
        """Map serialized form to byte-comparable form."""
        return data

    def validate(self, data: bytes) -> None:
        self.deserialize(data)

    def freeze(self) -> "CQLType":
        return self

    def __repr__(self):
        return self.name

    def __eq__(self, other):
        return isinstance(other, CQLType) and repr(self) == repr(other)

    def __hash__(self):
        return hash(repr(self))


class AsciiType(CQLType):
    name = "ascii"

    def serialize(self, value) -> bytes:
        b = value.encode("ascii") if isinstance(value, str) else bytes(value)
        b.decode("ascii")
        return b

    def deserialize(self, data: bytes):
        return data.decode("ascii")


class TextType(CQLType):
    name = "text"

    def serialize(self, value) -> bytes:
        return value.encode("utf-8") if isinstance(value, str) else bytes(value)

    def deserialize(self, data: bytes):
        return data.decode("utf-8")


class BlobType(CQLType):
    name = "blob"

    def serialize(self, value) -> bytes:
        return bytes(value)

    def deserialize(self, data: bytes):
        return bytes(data)

    def validate(self, data: bytes) -> None:
        pass


class BooleanType(CQLType):
    name = "boolean"

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes):
        return data != b"\x00"

    def to_bytecomp(self, data: bytes) -> bytes:
        return b"\x01" if data != b"\x00" else b"\x00"


class _FixedIntType(CQLType):
    width = 4

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.width, "big", signed=True)

    def deserialize(self, data: bytes):
        return int.from_bytes(data, "big", signed=True)

    def to_bytecomp(self, data: bytes) -> bytes:
        # flip sign bit: unsigned lexicographic == signed numeric order
        return bytes([data[0] ^ 0x80]) + data[1:]

    def validate(self, data: bytes) -> None:
        if len(data) != self.width:
            raise ValueError(f"{self.name}: expected {self.width} bytes, got {len(data)}")


class TinyIntType(_FixedIntType):
    name = "tinyint"
    width = 1


class SmallIntType(_FixedIntType):
    name = "smallint"
    width = 2


class Int32Type(_FixedIntType):
    name = "int"
    width = 4


class LongType(_FixedIntType):
    name = "bigint"
    width = 8


class CounterColumnType(LongType):
    name = "counter"
    is_counter = True


class TimestampType(_FixedIntType):
    """Milliseconds since epoch, signed 64-bit (db/marshal/TimestampType)."""
    name = "timestamp"
    width = 8

    def serialize(self, value) -> bytes:
        if isinstance(value, datetime):
            value = int(value.timestamp() * 1000)
        return super().serialize(value)

    def deserialize(self, data: bytes):
        ms = int.from_bytes(data, "big", signed=True)
        return datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)


class SimpleDateType(CQLType):
    """Unsigned 32-bit days with 2^31 = 1970-01-01 (db/marshal/SimpleDateType)."""
    name = "date"

    def serialize(self, value) -> bytes:
        if isinstance(value, date) and not isinstance(value, datetime):
            days = (value - date(1970, 1, 1)).days
        else:
            days = int(value)
        return (days + _EPOCH_DATE_BIAS).to_bytes(4, "big")

    def deserialize(self, data: bytes):
        days = int.from_bytes(data, "big") - _EPOCH_DATE_BIAS
        return date(1970, 1, 1) + timedelta(days=days)

    def to_bytecomp(self, data: bytes) -> bytes:
        return data  # already unsigned big-endian


class TimeType(CQLType):
    """Nanoseconds since midnight, signed 64-bit, always >= 0."""
    name = "time"

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(8, "big", signed=True)

    def deserialize(self, data: bytes):
        return int.from_bytes(data, "big", signed=True)

    def to_bytecomp(self, data: bytes) -> bytes:
        return data  # non-negative => plain BE ordering is numeric


class FloatType(CQLType):
    name = "float"

    def serialize(self, value) -> bytes:
        return struct.pack(">f", value)

    def deserialize(self, data: bytes):
        return struct.unpack(">f", data)[0]

    def to_bytecomp(self, data: bytes) -> bytes:
        return bytecomp.encode_float(struct.unpack(">f", data)[0], double=False)


class DoubleType(CQLType):
    name = "double"

    def serialize(self, value) -> bytes:
        return struct.pack(">d", value)

    def deserialize(self, data: bytes):
        return struct.unpack(">d", data)[0]

    def to_bytecomp(self, data: bytes) -> bytes:
        return bytecomp.encode_float(struct.unpack(">d", data)[0], double=True)


class IntegerType(CQLType):
    """Arbitrary-precision integer (varint): two's-complement BE bytes."""
    name = "varint"

    def serialize(self, value) -> bytes:
        v = int(value)
        # minimal two's-complement length (BigInteger.toByteArray semantics)
        length = ((v if v >= 0 else -v - 1).bit_length() // 8) + 1
        return v.to_bytes(length, "big", signed=True)

    def deserialize(self, data: bytes):
        return int.from_bytes(data, "big", signed=True)

    def to_bytecomp(self, data: bytes) -> bytes:
        return bytecomp.encode_varint(self.deserialize(data))


class DecimalType(CQLType):
    """scale (int32 BE) + unscaled varint (db/marshal/DecimalType)."""
    name = "decimal"

    def serialize(self, value) -> bytes:
        d = Decimal(value)
        sign, digits, exp = d.as_tuple()
        unscaled = int("".join(map(str, digits)))
        if sign:
            unscaled = -unscaled
        scale = -exp
        iv = IntegerType().serialize(unscaled)
        return struct.pack(">i", scale) + iv

    def deserialize(self, data: bytes):
        scale = struct.unpack_from(">i", data)[0]
        unscaled = int.from_bytes(data[4:], "big", signed=True)
        return Decimal(unscaled).scaleb(-scale)

    def to_bytecomp(self, data: bytes) -> bytes:
        """Order-preserving decimal: sign class byte, then exponent
        (complemented for negatives), then normalised mantissa digits."""
        d = self.deserialize(data)
        if d == 0:
            return b"\x80"
        sign, digits, exp = d.normalize().as_tuple()
        # value = mantissa(0.d1d2..) * 10^adj  with d1 != 0
        adj = exp + len(digits)
        mant = bytes(d + 1 for d in digits)  # digits 1..10, avoids 0x00
        eb = bytecomp.encode_int(adj, 4)
        if not sign:
            return b"\xc0" + eb + mant
        # negative: flip exponent and mantissa order
        inv_eb = bytes(0xFF - b for b in eb)
        inv_m = bytes(0xFF - b for b in mant)
        return b"\x40" + inv_eb + inv_m + b"\xff"  # terminator keeps prefix order


class UUIDType(CQLType):
    """Compare by version first, then v1 timestamp, then raw bytes
    (db/marshal/UUIDType.java compareCustom)."""
    name = "uuid"

    def serialize(self, value) -> bytes:
        if isinstance(value, uuid_mod.UUID):
            return value.bytes
        if isinstance(value, str):
            return uuid_mod.UUID(value).bytes
        return bytes(value)

    def deserialize(self, data: bytes):
        return uuid_mod.UUID(bytes=bytes(data))

    def to_bytecomp(self, data: bytes) -> bytes:
        u = uuid_mod.UUID(bytes=bytes(data))
        version = u.version or 0
        out = bytes([version])
        if version == 1:
            out += u.time.to_bytes(8, "big")
        return out + data

    def validate(self, data: bytes) -> None:
        if len(data) != 16:
            raise ValueError("uuid must be 16 bytes")


class TimeUUIDType(UUIDType):
    name = "timeuuid"

    def to_bytecomp(self, data: bytes) -> bytes:
        u = uuid_mod.UUID(bytes=bytes(data))
        return u.time.to_bytes(8, "big") + data

    def validate(self, data: bytes) -> None:
        super().validate(data)
        if uuid_mod.UUID(bytes=bytes(data)).version != 1:
            raise ValueError("timeuuid must be a version-1 uuid")


class InetAddressType(CQLType):
    name = "inet"

    def serialize(self, value) -> bytes:
        if isinstance(value, (bytes, bytearray)):
            return bytes(value)
        return ipaddress.ip_address(value).packed

    def deserialize(self, data: bytes):
        if len(data) == 4:
            return socket.inet_ntop(socket.AF_INET, data)
        return socket.inet_ntop(socket.AF_INET6, data)

    def validate(self, data: bytes) -> None:
        if len(data) not in (4, 16):
            raise ValueError("inet must be 4 or 16 bytes")


class DurationType(CQLType):
    """(months, days, nanos) signed vints (db/marshal/DurationType).
    Not orderable (cannot be a clustering column) — no to_bytecomp."""
    name = "duration"

    def serialize(self, value) -> bytes:
        months, days, nanos = value
        out = bytearray()
        vi.write_signed_vint(months, out)
        vi.write_signed_vint(days, out)
        vi.write_signed_vint(nanos, out)
        return bytes(out)

    def deserialize(self, data: bytes):
        months, pos = vi.read_signed_vint(data, 0)
        days, pos = vi.read_signed_vint(data, pos)
        nanos, _ = vi.read_signed_vint(data, pos)
        return (months, days, nanos)

    def to_bytecomp(self, data: bytes) -> bytes:
        raise TypeError("duration is not orderable")


class EmptyType(CQLType):
    name = "empty"

    def serialize(self, value) -> bytes:
        return b""

    def deserialize(self, data: bytes):
        if data:
            raise ValueError("empty type must have zero-length value")
        return None


# ------------------------------------------------------------ collections --

def _pack_elems(elems: list[bytes]) -> bytes:
    """Native-protocol collection body: count then [len][bytes] per element
    (len=-1 encodes null)."""
    out = bytearray(struct.pack(">i", len(elems)))
    for e in elems:
        if e is None:
            out += struct.pack(">i", -1)
        else:
            out += struct.pack(">i", len(e)) + e
    return bytes(out)


def _unpack_elems(data: bytes) -> list[bytes]:
    n = struct.unpack_from(">i", data, 0)[0]
    pos = 4
    out = []
    for _ in range(n):
        ln = struct.unpack_from(">i", data, pos)[0]
        pos += 4
        if ln < 0:
            out.append(None)
        else:
            out.append(bytes(data[pos:pos + ln]))
            pos += ln
    return out


class ListType(CQLType):
    is_collection = True

    def __init__(self, elem: CQLType, frozen: bool = False):
        self.elem = elem
        self.frozen = frozen
        self.is_multicell = not frozen

    @property
    def name(self):
        inner = f"list<{self.elem!r}>"
        return f"frozen<{inner}>" if self.frozen else inner

    def freeze(self):
        return ListType(self.elem, frozen=True)

    def serialize(self, value) -> bytes:
        return _pack_elems([self.elem.serialize(v) for v in value])

    def deserialize(self, data: bytes):
        return [self.elem.deserialize(e) for e in _unpack_elems(data)]

    def to_bytecomp(self, data: bytes) -> bytes:
        elems = _unpack_elems(data)
        return bytecomp.encode_composite(
            [self.elem.to_bytecomp(e) for e in elems])


class SetType(ListType):
    def __init__(self, elem: CQLType, frozen: bool = False):
        super().__init__(elem, frozen)

    @property
    def name(self):
        inner = f"set<{self.elem!r}>"
        return f"frozen<{inner}>" if self.frozen else inner

    def freeze(self):
        return SetType(self.elem, frozen=True)

    def serialize(self, value) -> bytes:
        # store in comparator (byte-comparable) element order
        elems = sorted((self.elem.serialize(v) for v in value),
                       key=self.elem.to_bytecomp)
        return _pack_elems(elems)

    def deserialize(self, data: bytes):
        return {self.elem.deserialize(e) for e in _unpack_elems(data)}

    def to_bytecomp(self, data: bytes) -> bytes:
        elems = sorted((self.elem.to_bytecomp(e) for e in _unpack_elems(data)))
        return bytecomp.encode_composite(elems)


class MapType(CQLType):
    is_collection = True

    def __init__(self, key: CQLType, val: CQLType, frozen: bool = False):
        self.key = key
        self.val = val
        self.frozen = frozen
        self.is_multicell = not frozen

    @property
    def name(self):
        inner = f"map<{self.key!r}, {self.val!r}>"
        return f"frozen<{inner}>" if self.frozen else inner

    def freeze(self):
        return MapType(self.key, self.val, frozen=True)

    def serialize(self, value) -> bytes:
        # comparator (byte-comparable) key order, like SetType
        items = sorted(((self.key.serialize(k), self.val.serialize(v))
                        for k, v in value.items()),
                       key=lambda kv: self.key.to_bytecomp(kv[0]))
        out = bytearray(struct.pack(">i", len(items)))
        for k, v in items:
            out += struct.pack(">i", len(k)) + k
            out += struct.pack(">i", len(v)) + v
        return bytes(out)

    def deserialize(self, data: bytes):
        n = struct.unpack_from(">i", data, 0)[0]
        pos = 4
        out = {}
        for _ in range(n):
            lk = struct.unpack_from(">i", data, pos)[0]
            pos += 4
            k = data[pos:pos + lk]
            pos += lk
            lv = struct.unpack_from(">i", data, pos)[0]
            pos += 4
            v = data[pos:pos + lv]
            pos += lv
            out[self.key.deserialize(k)] = self.val.deserialize(v)
        return out

    def to_bytecomp(self, data: bytes) -> bytes:
        d = self.deserialize(data)
        comps = []
        for k in sorted(d, key=lambda k: self.key.to_bytecomp(self.key.serialize(k))):
            comps.append(self.key.to_bytecomp(self.key.serialize(k)))
            comps.append(self.val.to_bytecomp(self.val.serialize(d[k])))
        return bytecomp.encode_composite(comps)


class TupleType(CQLType):
    def __init__(self, elems: list[CQLType]):
        self.elems = elems

    @property
    def name(self):
        return f"tuple<{', '.join(map(repr, self.elems))}>"

    def serialize(self, value) -> bytes:
        out = bytearray()
        for t, v in zip(self.elems, value):
            if v is None:
                out += struct.pack(">i", -1)
            else:
                s = t.serialize(v)
                out += struct.pack(">i", len(s)) + s
        return bytes(out)

    def deserialize(self, data: bytes):
        out = []
        pos = 0
        for t in self.elems:
            if pos >= len(data):
                out.append(None)
                continue
            ln = struct.unpack_from(">i", data, pos)[0]
            pos += 4
            if ln < 0:
                out.append(None)
            else:
                out.append(t.deserialize(data[pos:pos + ln]))
                pos += ln
        return tuple(out)

    def to_bytecomp(self, data: bytes) -> bytes:
        vals = self.deserialize(data)
        comps = []
        for t, v in zip(self.elems, vals):
            comps.append(b"" if v is None else b"\x01" + t.to_bytecomp(t.serialize(v)))
        return bytecomp.encode_composite(comps)


class UserType(TupleType):
    """Frozen UDT: same wire format as a tuple plus field names."""

    def __init__(self, keyspace: str, type_name: str, field_names: list[str],
                 field_types: list[CQLType]):
        super().__init__(field_types)
        self.keyspace = keyspace
        self.type_name = type_name
        self.field_names = field_names

    @property
    def name(self):
        return self.type_name

    def serialize(self, value) -> bytes:
        if isinstance(value, dict):
            value = tuple(value.get(f) for f in self.field_names)
        return super().serialize(value)

    def deserialize(self, data: bytes):
        vals = super().deserialize(data)
        return dict(zip(self.field_names, vals))


class VectorType(CQLType):
    """Fixed-dimension float32 vector (db/marshal/VectorType.java:45) —
    the ANN/SAI showcase type. Serialized as dim * 4 BE floats."""

    def __init__(self, elem: CQLType, dimension: int):
        if not isinstance(elem, FloatType):
            # reference supports any element type; we start with float32
            raise ValueError("vector element type must be float (round 1)")
        self.elem = elem
        self.dimension = dimension

    @property
    def name(self):
        return f"vector<float, {self.dimension}>"

    def serialize(self, value) -> bytes:
        if len(value) != self.dimension:
            raise ValueError(f"vector dimension mismatch: {len(value)} != {self.dimension}")
        return struct.pack(f">{self.dimension}f", *value)

    def deserialize(self, data: bytes):
        return list(struct.unpack(f">{self.dimension}f", data))

    def validate(self, data: bytes) -> None:
        if len(data) != 4 * self.dimension:
            raise ValueError("bad vector length")


# ---------------------------------------------------------------- parsing --

_SIMPLE_TYPES: dict[str, CQLType] = {}
for _cls in (AsciiType, TextType, BlobType, BooleanType, TinyIntType,
             SmallIntType, Int32Type, LongType, CounterColumnType, FloatType,
             DoubleType, DecimalType, IntegerType, TimestampType,
             SimpleDateType, TimeType, UUIDType, TimeUUIDType,
             InetAddressType, DurationType, EmptyType):
    _SIMPLE_TYPES[_cls.name] = _cls()
_SIMPLE_TYPES["varchar"] = _SIMPLE_TYPES["text"]

TYPE_REGISTRY = _SIMPLE_TYPES


def _split_args(s: str) -> list[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur).strip())
    return parts


def parse_type(s: str, udts: dict[str, UserType] | None = None) -> CQLType:
    """Parse a CQL type string, e.g. 'map<text, frozen<list<int>>>'."""
    s = s.strip()
    low = s.lower()
    if low in _SIMPLE_TYPES:
        return _SIMPLE_TYPES[low]
    if low.startswith("frozen<") and s.endswith(">"):
        return parse_type(s[7:-1], udts).freeze()
    if low.startswith("list<") and s.endswith(">"):
        return ListType(parse_type(s[5:-1], udts))
    if low.startswith("set<") and s.endswith(">"):
        return SetType(parse_type(s[4:-1], udts))
    if low.startswith("map<") and s.endswith(">"):
        k, v = _split_args(s[4:-1])
        return MapType(parse_type(k, udts), parse_type(v, udts))
    if low.startswith("tuple<") and s.endswith(">"):
        return TupleType([parse_type(a, udts) for a in _split_args(s[6:-1])])
    if low.startswith("vector<") and s.endswith(">"):
        elem, dim = _split_args(s[7:-1])
        return VectorType(parse_type(elem, udts), int(dim))
    if udts and low in udts:
        return udts[low]
    raise ValueError(f"unknown type: {s!r}")
