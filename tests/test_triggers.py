"""Triggers: coordinator-side mutation augmentation.

Reference: triggers/TriggerExecutor.java + ITrigger (CREATE TRIGGER ...
USING 'class' where the class must already be installed on the node —
DDL names code, never ships it)."""
import os
import textwrap

import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.cql.execution import InvalidRequest
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine

AUDIT_TRIGGER = textwrap.dedent("""
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    from cassandra_tpu.storage.mutation import Mutation
    from cassandra_tpu.utils import timeutil

    def audit(table, mutation, backend):
        at = backend.schema.get_table(table.keyspace, "audit_log")
        ts = timeutil.now_micros()
        m = Mutation(at.id, mutation.pk)
        m.add(b"", COL_ROW_LIVENESS, b"", b"", ts)
        m.add(b"", at.columns["n"].column_id, b"",
              at.columns["n"].cql_type.serialize(len(mutation.ops)), ts)
        return [m]

    def boom(table, mutation, backend):
        raise RuntimeError("no writes for you")
""")


def _engine(tmp_path, name="d"):
    return StorageEngine(str(tmp_path / name), Schema(),
                         commitlog_sync="batch")


def _install(eng, body=AUDIT_TRIGGER, fname="auditmod"):
    os.makedirs(eng.triggers.directory, exist_ok=True)
    with open(os.path.join(eng.triggers.directory, f"{fname}.py"),
              "w") as f:
        f.write(body)


def test_trigger_augments_writes(tmp_path):
    eng = _engine(tmp_path)
    _install(eng)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("CREATE TABLE audit_log (k int PRIMARY KEY, n int)")
    s.execute("CREATE TRIGGER aud ON kv USING 'auditmod:audit'")
    s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    rows = s.execute("SELECT k, n FROM audit_log").rows
    assert len(rows) == 1 and rows[0][0] == 1 and rows[0][1] >= 1
    # extras do not re-trigger (audit of audit would loop)
    s.execute("DROP TRIGGER aud ON kv")
    s.execute("INSERT INTO kv (k, v) VALUES (2, 'y')")
    assert len(s.execute("SELECT k FROM audit_log").rows) == 1
    eng.close()


def test_trigger_requires_installed_file(tmp_path):
    """DDL cannot ship code: USING must name a file the operator
    already placed in <data_dir>/triggers (conf/triggers role)."""
    eng = _engine(tmp_path)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    with pytest.raises(InvalidRequest, match="not installed"):
        s.execute("CREATE TRIGGER t ON kv USING 'ghost:fn'")
    with pytest.raises(InvalidRequest):
        s.execute("CREATE TRIGGER t ON kv USING '../evil:fn'")
    eng.close()


def test_trigger_failure_aborts_statement(tmp_path):
    """Augmentation failure fails the write BEFORE the base mutation
    applies (TriggerExecutor: exceptions propagate to the client)."""
    eng = _engine(tmp_path)
    _install(eng)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("CREATE TRIGGER t ON kv USING 'auditmod:boom'")
    from cassandra_tpu.service.triggers import TriggerError
    with pytest.raises(TriggerError):
        s.execute("INSERT INTO kv (k, v) VALUES (5, 'x')")
    assert s.execute("SELECT k FROM kv").rows == []
    eng.close()


def test_trigger_persists_across_restart(tmp_path):
    eng = _engine(tmp_path)
    _install(eng)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("CREATE TABLE audit_log (k int PRIMARY KEY, n int)")
    s.execute("CREATE TRIGGER aud ON kv USING 'auditmod:audit'")
    eng.close()

    eng2 = _engine(tmp_path)
    s2 = Session(eng2, keyspace="ks")
    s2.execute("INSERT INTO kv (k, v) VALUES (9, 'z')")
    assert [r[0] for r in s2.execute("SELECT k FROM audit_log").rows] \
        == [9]
    # duplicate name rejected; IF NOT EXISTS tolerated
    with pytest.raises(InvalidRequest):
        s2.execute("CREATE TRIGGER aud ON kv USING 'auditmod:audit'")
    s2.execute("CREATE TRIGGER IF NOT EXISTS aud ON kv "
               "USING 'auditmod:audit'")
    eng2.close()


def test_trigger_in_logged_batch(tmp_path):
    """A logged batch journals trigger output with the base writes."""
    eng = _engine(tmp_path)
    _install(eng)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("CREATE TABLE audit_log (k int PRIMARY KEY, n int)")
    s.execute("CREATE TRIGGER aud ON kv USING 'auditmod:audit'")
    s.execute("BEGIN BATCH "
              "INSERT INTO kv (k, v) VALUES (1, 'a'); "
              "INSERT INTO kv (k, v) VALUES (2, 'b'); "
              "APPLY BATCH")
    assert sorted(r[0] for r in
                  s.execute("SELECT k FROM audit_log").rows) == [1, 2]
    eng.close()


def test_trigger_column_name_still_parses(tmp_path):
    """'trigger' stays an UNRESERVED keyword: schemas that used it as
    an identifier keep parsing (their schema-log DDL must replay)."""
    eng = _engine(tmp_path)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE evt (trigger text PRIMARY KEY, n int)")
    s.execute("INSERT INTO evt (trigger, n) VALUES ('go', 1)")
    assert s.execute("SELECT trigger, n FROM evt").rows == [("go", 1)]
    eng.close()


def test_trigger_gone_from_recreated_keyspace(tmp_path):
    eng = _engine(tmp_path)
    _install(eng)
    s = Session(eng)
    for _round in range(2):
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        s.execute("CREATE TABLE audit_log (k int PRIMARY KEY, n int)")
        if _round == 0:
            s.execute("CREATE TRIGGER aud ON kv USING 'auditmod:audit'")
            s.execute("DROP KEYSPACE ks")
    # recreated keyspace has NO trigger: writes are not augmented
    s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    assert s.execute("SELECT k FROM audit_log").rows == []
    eng.close()


def test_missing_file_after_restart_fails_writes_visibly(tmp_path):
    """If the trigger file disappears, the trigger comes back BROKEN:
    writes fail with a clear error instead of silently skipping
    augmentation (reference: missing ITrigger class fails the write)."""
    from cassandra_tpu.service.triggers import TriggerError
    eng = _engine(tmp_path)
    _install(eng)
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("CREATE TABLE audit_log (k int PRIMARY KEY, n int)")
    s.execute("CREATE TRIGGER aud ON kv USING 'auditmod:audit'")
    eng.close()
    os.remove(os.path.join(str(tmp_path / "d"), "triggers",
                           "auditmod.py"))
    eng2 = _engine(tmp_path)
    s2 = Session(eng2, keyspace="ks")
    with pytest.raises(TriggerError, match="unusable"):
        s2.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    s2.execute("DROP TRIGGER aud ON kv")        # operator clears it
    s2.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    assert s2.execute("SELECT k FROM kv").rows == [(1,)]
    eng2.close()


def test_trigger_ddl_respects_auth(tmp_path):
    from cassandra_tpu.service.auth import UnauthorizedError
    eng = StorageEngine(str(tmp_path / "auth"), Schema(),
                        commitlog_sync="batch", auth_enabled=True)
    _install(eng)
    s = Session(eng, user="cassandra", password="cassandra")
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    s.execute("CREATE ROLE peon WITH PASSWORD = 'x' AND LOGIN = true")
    s2 = Session(eng, keyspace="ks", user="peon", password="x")
    with pytest.raises(UnauthorizedError):
        s2.execute("CREATE TRIGGER t ON kv USING 'auditmod:audit'")
    eng.close()
