"""clock-discipline: modules that promise an injectable clock must not
bind the real one.

Two module populations, two rules:

1. MARKED modules — carry `# ctpulint: clock-injectable` and expose a
   clock seam (`clock=` parameter, module-level `CLOCK`). Direct CALLS
   to `time.time/monotonic/perf_counter/time_ns/sleep` are violations:
   they bypass the seam, so tests and the simulator silently get real
   time. References (`clock=time.monotonic` as a default) are the seam
   itself and stay legal. The floor set below MUST be marked — deleting
   a marker is reported, so the discipline cannot rot away.

2. SIM-PATCHED modules — listed in `sim/scheduler.py::_PATCH_MODULES`;
   the simulator swaps their module-level `time`/`threading` attributes
   for virtual ones. Module-attribute calls (`time.monotonic()`) are
   therefore FINE; what breaks determinism is anything that captures
   the real module before patching:

     * `from time import sleep` / `import time as _t` (the patched
       attribute is named `time`; aliases escape), and
     * `time.xxx` as a DEFAULT ARGUMENT value (evaluated at import
       time — the captured function is the real clock forever, even
       under simulation).
"""
from __future__ import annotations

import ast

from ..report import Violation

NAME = "clock-discipline"

MARKER = "clock-injectable"

# modules that must carry the marker (the declared clock-seam surface;
# ISSUE 13 names them)
REQUIRED_MARKED = (
    "cassandra_tpu.service.slo",
    "cassandra_tpu.utils.ratelimit",
    "cassandra_tpu.utils.pipeline_ledger",
    "cassandra_tpu.utils.timeutil",
)

CLOCK_FNS = {"time", "monotonic", "perf_counter", "time_ns", "sleep"}

SIM_SCHED_MOD = "cassandra_tpu.sim.scheduler"


def sim_patched_modules(index) -> list[str]:
    """Read _PATCH_MODULES out of sim/scheduler.py's AST so the check
    and the simulator can never disagree about which modules are
    virtual-clock territory."""
    mod = index.modules.get(SIM_SCHED_MOD)
    if mod is None:
        return []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "_PATCH_MODULES" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def _time_aliases(mod) -> set[str]:
    """Names the module binds to the real `time` module (incl.
    function-level imports — ast.walk sees them)."""
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    out.add(a.asname or "time")
    return out


def _marked_violations(mod) -> list[Violation]:
    aliases = _time_aliases(mod)
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id in aliases and f.attr in CLOCK_FNS:
            out.append(Violation(
                NAME, mod.relpath, node.lineno,
                f"direct `{f.value.id}.{f.attr}()` call in a "
                f"clock-injectable module — route it through the "
                f"module's clock seam so tests/sim stay virtual"))
    return out


def _sim_violations(mod) -> list[Violation]:
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "time", "threading"):
            out.append(Violation(
                NAME, mod.relpath, node.lineno,
                f"`from {node.module} import ...` in a sim-patched "
                f"module captures the real module — the simulator "
                f"patches the `{node.module}` attribute only; use "
                f"module-level `import {node.module}`"))
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("time", "threading") and a.asname \
                        and a.asname != a.name:
                    out.append(Violation(
                        NAME, mod.relpath, node.lineno,
                        f"`import {a.name} as {a.asname}` in a "
                        f"sim-patched module escapes the simulator's "
                        f"attribute patch (it replaces `{a.name}`, "
                        f"not `{a.asname}`)"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = (node.args.defaults
                        + [d for d in node.args.kw_defaults if d])
            for d in defaults:
                for sub in ast.walk(d):
                    if isinstance(sub, ast.Attribute) and \
                            isinstance(sub.value, ast.Name) and \
                            sub.value.id in ("time", "threading"):
                        out.append(Violation(
                            NAME, mod.relpath, node.lineno,
                            f"default argument `{sub.value.id}."
                            f"{sub.attr}` in sim-patched module is "
                            f"bound at import time, BEFORE the "
                            f"simulator patches the module — default "
                            f"to None and bind inside the function"))
    return out


def run(index) -> list[Violation]:
    out = []
    for name in REQUIRED_MARKED:
        mod = index.modules.get(name)
        if mod is None:
            continue
        if MARKER not in mod.markers:
            out.append(Violation(
                NAME, mod.relpath, 1,
                f"module must declare `# ctpulint: {MARKER}` — it is "
                f"part of the injectable-clock surface (and the "
                f"marker is what activates this check on it)"))
    for mod in index.modules.values():
        if MARKER in mod.markers:
            out.extend(_marked_violations(mod))
    for name in sim_patched_modules(index):
        mod = index.modules.get(name)
        if mod is not None:
            out.extend(_sim_violations(mod))
    return out
