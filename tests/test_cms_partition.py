"""CMS linearizability under partition: the minority side CANNOT commit
metadata (DDL or topology), the majority can, and healing produces ONE
log — no fork, no displaced client-acked entries.

Reference: tcm/PaxosBackedProcessor.java:57 (every metadata commit goes
through Paxos on the CMS replica set), tcm/Commit.java. The round-3
designated-coordinator scheme allowed both sides of a partition to
append the same epoch; this test pins the property that replaced it.

Rig: three in-process nodes with PER-NODE Schema/Ring/SchemaSync (the
noded deployment shape) over a LocalTransport, whose MessageFilters
implement the partition.
"""
import time

import pytest

from cassandra_tpu.cluster.cms import MetadataUnavailable
from cassandra_tpu.cluster.messaging import LocalTransport
from cassandra_tpu.cluster.node import Node
from cassandra_tpu.cluster.ring import Endpoint, Ring, even_tokens
from cassandra_tpu.cluster.schema_sync import SchemaSync
from cassandra_tpu.schema import Schema


def _mk_cluster(tmp_path, n=3):
    eps = [Endpoint(f"node{i + 1}", host="127.0.0.1", port=0)
           for i in range(n)]
    tokens = even_tokens(n, vnodes=4)
    transport = LocalTransport()
    nodes = []
    for ep in eps:
        ring = Ring()
        for e, toks in zip(eps, tokens):
            ring.add_node(e, toks)
        node = Node(ep, str(tmp_path / ep.name), Schema(), ring,
                    transport, seeds=[eps[0]], gossip_interval=0.05)
        node.cluster_nodes = [node]
        node.schema_sync = SchemaSync(node, str(tmp_path / ep.name))
        node.gossiper.start()
        nodes.append(node)
    return transport, eps, nodes


def _wait(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _partition_node1(transport, eps):
    """Cut node1 off from node2+node3 in both directions."""
    transport.filters.drop(frm=eps[0])
    transport.filters.drop(to=eps[0])


def test_minority_cannot_commit_majority_can_no_fork(tmp_path):
    transport, eps, nodes = _mk_cluster(tmp_path)
    n1, n2, n3 = nodes
    try:
        _wait(lambda: all(n1.is_alive(e) for e in eps[1:])
              and n2.is_alive(eps[0]),
              msg="full liveness")
        # baseline entry committed cluster-wide
        s1 = n1.session()
        s1.execute("CREATE KEYSPACE ks WITH replication = "
                   "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        _wait(lambda: all(n.schema_sync.epoch >= 1 for n in nodes),
              msg="baseline epoch everywhere")

        _partition_node1(transport, eps)
        _wait(lambda: not n1.is_alive(eps[1])
              and not n1.is_alive(eps[2]),
              msg="node1 convicts the majority side")
        _wait(lambda: not n2.is_alive(eps[0]),
              msg="majority convicts node1")

        # ---- minority side: node1 (a CMS member, and the node the old
        # designated-coordinator scheme would have let commit!) must
        # FAIL, leaving no local residue
        with pytest.raises(MetadataUnavailable):
            s1.execute("CREATE TABLE ks.minority_t (k int PRIMARY KEY)")
        assert n1.schema_sync.epoch == 1
        with pytest.raises(KeyError):
            n1.schema.get_table("ks", "minority_t")
        # topology changes ride the same committed log: also refused
        with pytest.raises(MetadataUnavailable):
            n1.topology_commit({"op": "leave",
                                "node": {"name": "node3"}})

        # ---- majority side commits fine
        s2 = n2.session()
        s2.execute("CREATE TABLE ks.majority_t (k int PRIMARY KEY, "
                   "v text)")
        _wait(lambda: n2.schema_sync.epoch >= 2
              and n3.schema_sync.epoch >= 2,
              msg="majority epoch 2")
        t2 = n2.schema.get_table("ks", "majority_t")
        assert n3.schema.get_table("ks", "majority_t").id == t2.id
        # node1 (partitioned) knows nothing of it
        assert n1.schema_sync.epoch == 1

        # ---- heal: node1 catches up; ONE history, no fork
        transport.filters.clear()
        assert n1.schema_sync.pull_from_peers(timeout=5.0)
        _wait(lambda: n1.schema_sync.epoch >= 2, msg="node1 caught up")
        assert n1.schema.get_table("ks", "majority_t").id == t2.id
        logs = [n.schema_sync.entries_after(0) for n in nodes]
        assert logs[0] == logs[1] == logs[2]
        assert not any("minority_t" in rec[1] for rec in logs[0])

        # ---- and the healed node can commit again, on the SAME log
        _wait(lambda: n1.is_alive(eps[1]) and n1.is_alive(eps[2]),
              msg="liveness restored")
        s1.execute("CREATE TABLE ks.after_heal (k int PRIMARY KEY)")
        _wait(lambda: all(n.schema_sync.epoch >= 3 for n in nodes),
              msg="post-heal epoch everywhere")
        ids = {str(n.schema.get_table("ks", "after_heal").id)
               for n in nodes}
        assert len(ids) == 1
    finally:
        for n in nodes:
            n.shutdown()


def test_concurrent_commits_serialize_without_displacement(tmp_path):
    """Two CMS members committing concurrently: Paxos serializes them
    into DIFFERENT epochs; both statements survive (the round-3 scheme
    could displace one), and every node agrees on the order."""
    transport, eps, nodes = _mk_cluster(tmp_path)
    n1, n2, n3 = nodes
    try:
        _wait(lambda: all(n1.is_alive(e) for e in eps[1:])
              and all(n2.is_alive(e) for e in (eps[0], eps[2])),
              msg="full liveness")
        s1, s2 = n1.session(), n2.session()
        s1.execute("CREATE KEYSPACE ks WITH replication = "
                   "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        _wait(lambda: all(n.schema_sync.epoch >= 1 for n in nodes),
              msg="baseline epoch")

        import threading
        errs = []

        def ddl(sess, q):
            try:
                sess.execute(q)
            except Exception as e:       # surfaced below
                errs.append(e)

        t1 = threading.Thread(target=ddl, args=(
            s1, "CREATE TABLE ks.t_from_n1 (k int PRIMARY KEY)"))
        t2 = threading.Thread(target=ddl, args=(
            s2, "CREATE TABLE ks.t_from_n2 (k int PRIMARY KEY)"))
        t1.start()
        t2.start()
        t1.join(20)
        t2.join(20)
        assert not errs, errs

        _wait(lambda: all(n.schema_sync.epoch >= 3 for n in nodes),
              msg="both entries everywhere")
        logs = [n.schema_sync.entries_after(1) for n in nodes]
        assert logs[0] == logs[1] == logs[2]
        queries = [rec[1] for rec in logs[0]]
        assert sorted(queries) == [
            "CREATE TABLE ks.t_from_n1 (k int PRIMARY KEY)",
            "CREATE TABLE ks.t_from_n2 (k int PRIMARY KEY)"]
        # each table exists everywhere with one id
        for name in ("t_from_n1", "t_from_n2"):
            ids = {str(n.schema.get_table("ks", name).id) for n in nodes}
            assert len(ids) == 1, (name, ids)
    finally:
        for n in nodes:
            n.shutdown()
