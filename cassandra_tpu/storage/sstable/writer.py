"""SSTable writer: sorted CellBatches -> ctpu components.

Reference counterpart: io/sstable/format/SortedTableWriter.java:76 (append
loop), io/compress/CompressedSequentialWriter.java:43 (chunk+CRC write
path), BigTableWriter.java:237-254 (bloom + index build during append).

The writer consumes *sorted* batches (flush output or merge-kernel output),
cuts fixed-size segments, compresses each segment's three blocks through
the table codec's batch API (one FFI crossing per segment), and maintains
the bloom filter / partition directory / stats as it goes.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import zlib

import numpy as np

from ...ops.codec import CompressionParams
from ...schema import TableMetadata
from ...utils import bloom
from ..cellbatch import CellBatch
from .format import SEGMENT_CELLS, Component, Descriptor


class SSTableWriter:
    # trickle fsync (conf trickle_fsync role): push dirty pages to disk
    # WHILE later segments compress/serialize, so the commit-time fsync
    # only pays for the tail. Without it a large sstable's entire flush
    # hits the disk in one blocking call at finish() — measured as the
    # single largest compaction phase on this box (disk ~128 MiB/s
    # flushed vs ~2 GiB/s to page cache).
    TRICKLE_FSYNC_BYTES = 16 << 20

    def __init__(self, descriptor: Descriptor, table: TableMetadata,
                 estimated_partitions: int = 1024,
                 segment_cells: int = SEGMENT_CELLS):
        self.desc = descriptor
        self.table = table
        self.params: CompressionParams = table.params.compression
        self.compressor = self.params.compressor_or_noop()
        self.segment_cells = segment_cells
        self.K = None  # lanes, learned from first batch

        os.makedirs(descriptor.directory, exist_ok=True)
        # unbuffered: segment blocks are MB-sized memoryviews already —
        # BufferedWriter would only add a copy per write
        self._data = open(descriptor.tmp_path(Component.DATA), "wb",
                          buffering=0)
        self._data_crc = 0
        self._data_off = 0
        self._index_entries: list[bytes] = []
        self._bloom = bloom.BloomFilter.create(max(estimated_partitions, 16))
        # partition directory accumulators
        self._part_lane4: list[bytes] = []
        self._part_first_cell: list[int] = []
        self._part_pk: list[bytes] = []
        self._last_lane4: bytes | None = None
        # pending cells not yet cut into a segment
        self._pending: list[CellBatch] = []
        self._pending_cells = 0
        self._total_cells = 0
        self._stats = {
            "min_ts": None, "max_ts": None, "min_ldt": None, "max_ldt": None,
            "tombstones": 0,
        }
        self.level = 0   # LCS level (recorded in Statistics.db)
        # repairedAt epoch millis; 0 = unrepaired (reference
        # StatsMetadata.repairedAt — the repaired/unrepaired compaction
        # split and incremental repair key off this)
        self.repaired_at = 0
        self._finished = False
        self._sync_req = threading.Event()
        self._sync_stop = False
        self._sync_error: OSError | None = None
        self._bytes_since_sync = 0
        # started lazily on the first threshold crossing: small writers
        # (memtable flushes, mesh shards) never pay thread create/join,
        # and an abandoned writer (caller crashed before finish/abort)
        # leaks nothing
        self._syncer: threading.Thread | None = None

    # ---------------------------------------------------------------- api --

    def append(self, batch: CellBatch) -> None:
        """Append a sorted batch; cells must follow all previously appended
        cells in identity-lane order (enforced cheaply at segment cut)."""
        if len(batch) == 0:
            return
        if self.K is None:
            self.K = batch.n_lanes
        assert batch.n_lanes == self.K
        self._pending.append(batch)
        self._pending_cells += len(batch)
        while self._pending_cells >= self.segment_cells:
            self._cut_segment(self.segment_cells)

    def finish(self) -> dict:
        """Flush remaining cells, write all components, atomically rename.
        Returns the stats dict."""
        assert not self._finished
        while self._pending_cells > 0:
            self._cut_segment(min(self.segment_cells, self._pending_cells))
        if self.K is None:
            self.K = 13
        self._stop_syncer()   # join BEFORE the final fsync + close
        if self._sync_error is not None:
            raise self._sync_error
        self._data.flush()
        os.fsync(self._data.fileno())
        self._data.close()

        self._write_index()
        self._write_partitions()
        self._write_filter()
        stats = self._write_stats()
        self._write_digest()
        # TOC last, then atomic renames (TOC rename LAST = commit point).
        # Every component is fsynced before its rename and the directory
        # is fsynced after the TOC rename — otherwise a crash can persist
        # the commit point over truncated/unrenamed components.
        with open(self.desc.tmp_path(Component.TOC), "w") as f:
            f.write("\n".join(Component.ALL) + "\n")
            f.flush()
            os.fsync(f.fileno())
        for comp in Component.ALL:
            if comp != Component.TOC:
                self._fsync_path(self.desc.tmp_path(comp))
                os.replace(self.desc.tmp_path(comp), self.desc.path(comp))
        # component renames must be durable BEFORE the TOC commit point
        # lands, and the TOC rename itself needs a second dir sync
        self._fsync_path(self.desc.directory)
        os.replace(self.desc.tmp_path(Component.TOC),
                   self.desc.path(Component.TOC))
        self._fsync_path(self.desc.directory)
        self._finished = True
        return stats

    def _write_all(self, mv: memoryview) -> None:
        """Raw FileIO.write may write short (and caps single writes around
        2 GiB on Linux) — loop until every byte lands."""
        total = mv.nbytes
        while mv.nbytes:
            n = self._data.write(mv)
            if n is None or n <= 0:
                raise OSError("short write to Data.db")
            mv = mv[n:]
        self._bytes_since_sync += total
        if self._bytes_since_sync >= self.TRICKLE_FSYNC_BYTES:
            self._bytes_since_sync = 0
            if self._syncer is None:
                self._syncer = threading.Thread(
                    target=self._trickle_sync, daemon=True,
                    name="sstable-trickle-fsync")
                self._syncer.start()
            self._sync_req.set()       # syncer flushes in the background

    def _trickle_sync(self) -> None:
        while True:
            self._sync_req.wait()
            self._sync_req.clear()
            if self._sync_stop:
                return
            try:
                os.fsync(self._data.fileno())
            except OSError as e:
                # a writeback error (EIO/ENOSPC) is reported ONCE per
                # fd; swallowing it here would let finish()'s final
                # fsync succeed and commit an sstable with lost pages.
                # Record it — finish() re-raises before the commit point.
                self._sync_error = e
                return

    def _stop_syncer(self) -> None:
        # join blocks for at most one in-flight fsync, bounded by
        # TRICKLE_FSYNC_BYTES of dirty pages (~0.15s on this disk)
        if self._syncer is None:
            return
        self._sync_stop = True
        self._sync_req.set()
        self._syncer.join()

    @staticmethod
    def _fsync_path(path: str) -> None:
        """fsync a file or directory by path (directories need an fd too —
        the rename itself is only durable once the dir entry is synced)."""
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def abort(self) -> None:
        self._stop_syncer()
        if not self._data.closed:
            self._data.close()
        for comp in Component.ALL:
            p = self.desc.tmp_path(comp)
            if os.path.exists(p):
                os.remove(p)

    # ------------------------------------------------------------ internals

    def _take(self, n: int) -> CellBatch:
        """Pop exactly n cells from pending batches."""
        taken = []
        got = 0
        while got < n:
            b = self._pending[0]
            need = n - got
            if len(b) <= need:
                taken.append(b)
                self._pending.pop(0)
                got += len(b)
            else:
                taken.append(b.slice_range(0, need))
                self._pending[0] = b.slice_range(need, len(b))
                got = n
        self._pending_cells -= n
        return CellBatch.concat(taken) if len(taken) > 1 else taken[0]

    def _cut_segment(self, n: int) -> None:
        seg = self._take(n)
        # ordering guard: identity lanes must be lexicographically
        # non-decreasing across the whole stream
        first = seg.lanes[0].astype(">u4").tobytes()
        if self._last_lane_end is not None and first < self._last_lane_end:
            raise ValueError("appended cells out of order")
        if n > 1:
            a, b = seg.lanes[:-1], seg.lanes[1:]
            neq = a != b
            anyneq = neq.any(axis=1)
            if anyneq.any():
                fi = neq.argmax(axis=1)
                rows = np.arange(n - 1)
                if ((a[rows, fi] > b[rows, fi]) & anyneq).any():
                    raise ValueError("appended cells out of order")

        # --- partition directory + bloom
        lane4 = np.ascontiguousarray(seg.lanes[:, :4])
        part_new = np.ones(n, dtype=bool)
        part_new[1:] = (lane4[1:] != lane4[:-1]).any(axis=1)
        starts = np.flatnonzero(part_new)
        new_keys = []
        for s in starts:
            l4 = lane4[s].astype(">u4").tobytes()
            if l4 == self._last_lane4:
                continue  # partition continues from previous segment
            pk = seg.pk_map.get(l4)
            if pk is None:
                raise ValueError("pk_map missing partition key")
            self._part_lane4.append(l4)
            self._part_first_cell.append(self._total_cells + int(s))
            self._part_pk.append(pk)
            new_keys.append(pk)
            self._last_lane4 = l4
        self._bloom.add_batch(new_keys)

        # --- stats
        st = self._stats

        def _lo(key, v):
            st[key] = v if st[key] is None else min(st[key], v)

        def _hi(key, v):
            st[key] = v if st[key] is None else max(st[key], v)

        _lo("min_ts", int(seg.ts.min()))
        _hi("max_ts", int(seg.ts.max()))
        _lo("min_ldt", int(seg.ldt.min()))
        _hi("max_ldt", int(seg.ldt.max()))
        from ..cellbatch import DEATH_FLAGS
        self._stats["tombstones"] += int(
            ((seg.flags & DEATH_FLAGS) != 0).sum())

        # --- blocks: vectorized serialization into one scratch buffer,
        # then zero-copy scatter-gather compression (the previous
        # tobytes/join/ctypes staging copied every byte ~4x — measured as
        # the dominant write-path cost)
        off_rel = (seg.off - seg.off[0]).astype("<i8")
        vs_rel = (seg.val_start - seg.off[0]).astype("<i8")
        # ts 8 + ldt 4 + ttl 4 + flags 1 + off 8 + val_start 8 = 33 B/cell,
        # plus the off array's extra (n+1)th entry
        meta = np.empty(n * 33 + 8, dtype=np.uint8)
        pos = 0
        for arr, width in ((seg.ts.astype("<i8", copy=False), 8),
                           (seg.ldt.astype("<i4", copy=False), 4),
                           (seg.ttl.astype("<i4", copy=False), 4),
                           (seg.flags.astype("u1", copy=False), 1),
                           (off_rel, 8), (vs_rel, 8)):
            end = pos + (n + 1 if arr is off_rel else n) * width
            meta[pos:end] = np.ascontiguousarray(arr).view(np.uint8)
            pos = end
        meta = meta[:pos]
        lanes_b = np.ascontiguousarray(seg.lanes.astype("<u4", copy=False))
        payload_b = np.ascontiguousarray(seg.payload)
        blocks = [meta, lanes_b, payload_b]
        dst, dst_offs, sizes = self.compressor.compress_iov(blocks)
        # min_compress_ratio fallback: store uncompressed when too poor
        # (CompressedSequentialWriter.java:160-175 semantics)
        maxlen = self.params.max_compressed_length
        entry = struct.pack("<QI", self._data_off, n)
        for i, raw in enumerate(blocks):
            c = dst[int(dst_offs[i]):int(dst_offs[i]) + int(sizes[i])]
            if c.nbytes >= min(raw.nbytes, maxlen):
                c = raw
            mv = memoryview(c).cast("B")
            crc = zlib.crc32(mv)
            entry += struct.pack("<QQI", c.nbytes, raw.nbytes, crc)
            self._write_all(mv)
            self._data_crc = zlib.crc32(mv, self._data_crc)
            self._data_off += c.nbytes
        entry += seg.lanes[0].astype("<u4").tobytes()
        entry += seg.lanes[-1].astype("<u4").tobytes()
        self._index_entries.append(entry)
        self._total_cells += n
        self._last_lane_end = seg.lanes[-1].astype(">u4").tobytes()

    _last_lane_end: bytes | None = None

    def _write_index(self) -> None:
        with open(self.desc.tmp_path(Component.INDEX), "wb") as f:
            f.write(struct.pack("<III", len(self._index_entries), self.K,
                                self.segment_cells))
            for e in self._index_entries:
                f.write(e)

    def _write_partitions(self) -> None:
        with open(self.desc.tmp_path(Component.PARTITIONS), "wb") as f:
            np_count = len(self._part_lane4)
            f.write(struct.pack("<I", np_count))
            f.write(b"".join(self._part_lane4))
            f.write(np.array(self._part_first_cell,
                             dtype="<i8").tobytes())
            pk_off = np.zeros(np_count + 1, dtype="<i8")
            np.cumsum([len(p) for p in self._part_pk], out=pk_off[1:])
            f.write(pk_off.tobytes())
            f.write(b"".join(self._part_pk))

    def _write_filter(self) -> None:
        with open(self.desc.tmp_path(Component.FILTER), "wb") as f:
            f.write(self._bloom.serialize())

    def _write_stats(self) -> dict:
        stats = {
            "version": self.desc.version,
            "keyspace": self.table.keyspace,
            "table": self.table.name,
            "table_id": str(self.table.id),
            "n_lanes": self.K,
            "segment_cells": self.segment_cells,
            "n_cells": self._total_cells,
            "n_partitions": len(self._part_lane4),
            "compression": self.params.to_dict(),
            "level": self.level,
            "repaired_at": self.repaired_at,
            **self._stats,
        }
        with open(self.desc.tmp_path(Component.STATS), "w") as f:
            json.dump(stats, f)
        return stats

    def _write_digest(self) -> None:
        with open(self.desc.tmp_path(Component.DIGEST), "w") as f:
            f.write(f"{self._data_crc & 0xFFFFFFFF}\n")
