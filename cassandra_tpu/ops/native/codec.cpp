// Native chunk codecs for the SSTable I/O path.
//
// Role parity: the reference's chunk codecs are JNI libraries (lz4-java,
// snappy-java, zstd-jni; see reference io/compress/LZ4Compressor.java:39,
// SnappyCompressor.java:33). Here they are first-party C++: LZ4 block
// format and Snappy raw format, implemented from the public format specs
// (lz4_Block_format.md; snappy/format_description.txt), exposed via a C ABI
// consumed with ctypes (ops/codec.py). Batch entry points compress many
// chunks per call so the Python layer crosses the FFI once per flush, not
// once per 16KiB chunk.
//
// Build: ops/native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <cstddef>

#include <dlfcn.h>
#include <pthread.h>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- LZ4 -----
// LZ4 block format: sequences of
//   [token][lit-len ext*][literals][offset LE16][match-len ext*]
// token = (lit_len<<4) | (match_len-4), nibble 15 => extension bytes.
// Constraints honoured: last sequence is literals-only; matches end >= 12
// bytes before the end; offset in [1, 65535].

static const int MINMATCH = 4;

// Restricted distance candidate set for the POLICY match search (see
// lz4_compress below). All short lags 1..64 (columnar 25-byte META
// strides, shuffled lane byte-planes, periodic text) plus power-of-two
// long lags up to the format's 64KiB window. Ascending order is load-
// bearing: ties on run length resolve to the SMALLEST distance.
static const int LZ4_NDIST = 73;
static const uint16_t LZ4_DIST[LZ4_NDIST] = {
     1,  2,  3,  4,  5,  6,  7,  8,  9, 10, 11, 12, 13, 14, 15, 16,
    17, 18, 19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32,
    33, 34, 35, 36, 37, 38, 39, 40, 41, 42, 43, 44, 45, 46, 47, 48,
    49, 50, 51, 52, 53, 54, 55, 56, 57, 58, 59, 60, 61, 62, 63, 64,
    128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768};

// snappy's reference implementation sizes its table up to 2^14 —
// tuned separately from LZ4's (the measurements behind HASH_LOG=12
// were LZ4-only)
static const int SNAPPY_HASH_LOG = 14;

static inline uint32_t snappy_hash(uint32_t v) {
    return (v * 2654435761u) >> (32 - SNAPPY_HASH_LOG);
}

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

// worst-case compressed size (same bound as LZ4_compressBound)
int64_t lz4_max_compressed(int64_t n) {
    return n + n / 255 + 16;
}

// Deterministic POLICY encoder — returns compressed size, or -1 if dst
// too small.
//
// The device-side compressor (ops/device_compress.py) must emit blocks
// BYTE-IDENTICAL to this host encoder for any pool size × device on/off
// (check_compaction_ab.py's pinned contract), so the match search is a
// fixed policy rather than a hash-table heuristic: at every visited
// position take the longest forward run over the LZ4_DIST candidate
// set (ties → smallest distance), accept iff ≥ MINMATCH, else advance
// one byte. A hash-table matcher's output depends on probe/insertion
// order, which a data-parallel device scan cannot reproduce; an argmax
// over a fixed distance set is order-free and maps to one vectorized
// shifted-equality pass per distance.
static int64_t lz4_compress_policy(const uint8_t* src, int64_t srcLen,
                                   uint8_t* dst, int64_t dstCap) {
    if (srcLen == 0) {
        if (dstCap < 1) return -1;
        dst[0] = 0;  // token: 0 literals, no match
        return 1;
    }
    uint8_t* op = dst;
    uint8_t* oend = dst + dstCap;
    // matches may not start in the last 12 bytes (format rule); the
    // final 5 bytes must be literals
    const int64_t mflimit = srcLen - 12;
    int64_t pos = 0, anchor = 0;
    while (pos < mflimit) {
        const uint32_t cur = read32(src + pos);
        int64_t bestLen = 0, bestD = 0;
        for (int k = 0; k < LZ4_NDIST; k++) {
            const int64_t d = LZ4_DIST[k];
            if (d > pos) break;  // table ascends: rest are too far back
            // 4-byte prefilter: runs < MINMATCH are never accepted, so
            // skipping them leaves the policy's argmax unchanged
            if (read32(src + pos - d) != cur) continue;
            int64_t l = MINMATCH;
            while (pos + l < srcLen && src[pos - d + l] == src[pos + l])
                l++;
            if (l > bestLen) { bestLen = l; bestD = d; }
        }
        if (bestLen >= MINMATCH) {
            int64_t matchLen = bestLen;
            // clamp to the literal tail; pos < mflimit keeps the
            // clamped length ≥ 8 ≥ MINMATCH
            if (matchLen > srcLen - 5 - pos) matchLen = srcLen - 5 - pos;
            int64_t litLen = pos - anchor;
            int64_t need = 1 + litLen / 255 + 1 + litLen + 2 +
                           (matchLen - MINMATCH) / 255 + 1;
            if (op + need > oend) return -1;
            uint8_t* token = op++;
            if (litLen >= 15) {
                *token = 15 << 4;
                int64_t l = litLen - 15;
                while (l >= 255) { *op++ = 255; l -= 255; }
                *op++ = (uint8_t)l;
            } else {
                *token = (uint8_t)(litLen << 4);
            }
            memcpy(op, src + anchor, litLen);
            op += litLen;
            *op++ = (uint8_t)bestD;
            *op++ = (uint8_t)(bestD >> 8);
            int64_t ml = matchLen - MINMATCH;
            if (ml >= 15) {
                *token |= 15;
                ml -= 15;
                while (ml >= 255) { *op++ = 255; ml -= 255; }
                *op++ = (uint8_t)ml;
            } else {
                *token |= (uint8_t)ml;
            }
            pos += matchLen;
            anchor = pos;
        } else {
            pos++;
        }
    }
    // final literals
    int64_t litLen = srcLen - anchor;
    int64_t need = 1 + litLen / 255 + 1 + litLen;
    if (op + need > oend) return -1;
    uint8_t* token = op++;
    if (litLen >= 15) {
        *token = 15 << 4;
        int64_t l = litLen - 15;
        while (l >= 255) { *op++ = 255; l -= 255; }
        *op++ = (uint8_t)l;
    } else {
        *token = (uint8_t)(litLen << 4);
    }
    memcpy(op, src + anchor, litLen);
    op += litLen;
    return op - dst;
}

// first-party fallback — returns decompressed size, or -1 on
// malformed input / overflow
static int64_t lz4_decompress_fb(const uint8_t* src, int64_t srcLen,
                                 uint8_t* dst, int64_t dstCap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + srcLen;
    uint8_t* op = dst;
    uint8_t* oend = dst + dstCap;

    while (ip < iend) {
        uint8_t token = *ip++;
        // literals
        int64_t litLen = token >> 4;
        if (litLen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                litLen += b;
            } while (b == 255);
        }
        if (ip + litLen > iend || op + litLen > oend) return -1;
        memcpy(op, ip, litLen);
        ip += litLen;
        op += litLen;
        if (ip >= iend) break;  // last sequence has no match
        // match
        if (ip + 2 > iend) return -1;
        int64_t offset = ip[0] | (ip[1] << 8);
        ip += 2;
        if (offset == 0 || offset > op - dst) return -1;
        int64_t matchLen = (token & 15) + MINMATCH;
        if ((token & 15) == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                matchLen += b;
            } while (b == 255);
        }
        if (op + matchLen > oend) return -1;
        const uint8_t* match = op - offset;
        // overlapping copy must be byte-wise
        for (int64_t i = 0; i < matchLen; i++) op[i] = match[i];
        op += matchLen;
    }
    return op - dst;
}

// -------------------------------------------------------------- Snappy ----
// Raw snappy format: uvarint uncompressed length, then tagged elements:
//   tag&3 == 0: literal, len-1 in tag>>2 (60..63 => that many extra LE
//               length bytes)
//   tag&3 == 1: copy, len = 4 + ((tag>>2)&7), offset = ((tag>>5)<<8) | byte
//   tag&3 == 2: copy, len = 1 + (tag>>2), offset = LE16
//   tag&3 == 3: copy, len = 1 + (tag>>2), offset = LE32

int64_t snappy_max_compressed(int64_t n) {
    return 32 + n + n / 6;
}

static int64_t snappy_compress_fb(const uint8_t* src, int64_t srcLen,
                        uint8_t* dst, int64_t dstCap) {
    uint8_t* op = dst;
    uint8_t* oend = dst + dstCap;
    // uvarint length
    uint64_t v = (uint64_t)srcLen;
    do {
        if (op >= oend) return -1;
        uint8_t b = v & 0x7F;
        v >>= 7;
        *op++ = b | (v ? 0x80 : 0);
    } while (v);

    uint32_t table[1 << SNAPPY_HASH_LOG];
    memset(table, 0, sizeof(table));
    const uint8_t* ip = src;
    const uint8_t* anchor = src;
    const uint8_t* iend = src + srcLen;
    const uint8_t* limit = srcLen > 15 ? iend - 15 : src;

    auto emit_literal = [&](const uint8_t* from, int64_t len) -> bool {
        while (len > 0) {
            // largest emitted tag (62) carries 3 length bytes => n < 2^24
            int64_t chunk = len < (1 << 24) ? len : (1 << 24);
            int64_t n = chunk - 1;
            if (n < 60) {
                if (op + 1 + chunk > oend) return false;
                *op++ = (uint8_t)(n << 2);
            } else if (n < 256) {
                if (op + 2 + chunk > oend) return false;
                *op++ = 60 << 2;
                *op++ = (uint8_t)n;
            } else if (n < 65536) {
                if (op + 3 + chunk > oend) return false;
                *op++ = 61 << 2;
                *op++ = (uint8_t)n;
                *op++ = (uint8_t)(n >> 8);
            } else {
                if (op + 5 + chunk > oend) return false;
                *op++ = 62 << 2;
                *op++ = (uint8_t)n;
                *op++ = (uint8_t)(n >> 8);
                *op++ = (uint8_t)(n >> 16);
            }
            memcpy(op, from, chunk);
            op += chunk;
            from += chunk;
            len -= chunk;
        }
        return true;
    };
    auto emit_copy = [&](int64_t offset, int64_t len) -> bool {
        // len up to 64 per element; offset <= 65535 (we never match farther)
        while (len >= 68) {
            if (op + 3 > oend) return false;
            *op++ = (63 << 2) | 2;
            *op++ = (uint8_t)offset;
            *op++ = (uint8_t)(offset >> 8);
            len -= 64;
        }
        if (len > 64) {
            // emit 60, leave >= 4
            if (op + 3 > oend) return false;
            *op++ = (59 << 2) | 2;
            *op++ = (uint8_t)offset;
            *op++ = (uint8_t)(offset >> 8);
            len -= 60;
        }
        if (len >= 4 && len <= 11 && offset < 2048) {
            if (op + 2 > oend) return false;
            *op++ = (uint8_t)(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
            *op++ = (uint8_t)offset;
        } else {
            if (op + 3 > oend) return false;
            *op++ = (uint8_t)(((len - 1) << 2) | 2);
            *op++ = (uint8_t)offset;
            *op++ = (uint8_t)(offset >> 8);
        }
        return true;
    };

    if (srcLen > 15) {
        ip++;
        while (ip < limit) {
            uint32_t h = snappy_hash(read32(ip));
            const uint8_t* match = src + table[h];
            table[h] = (uint32_t)(ip - src);
            if (match < ip && (ip - match) <= 65535 &&
                read32(match) == read32(ip)) {
                const uint8_t* mi = match + 4;
                const uint8_t* ii = ip + 4;
                while (ii < iend && *ii == *mi) { ii++; mi++; }
                int64_t matchLen = ii - ip;
                if (!emit_literal(anchor, ip - anchor)) return -1;
                if (!emit_copy(ip - match, matchLen)) return -1;
                ip += matchLen;
                anchor = ip;
                if (ip < limit)
                    table[snappy_hash(read32(ip - 1))] =
                        (uint32_t)(ip - 1 - src);
            } else {
                ip++;
            }
        }
    }
    if (iend > anchor && !emit_literal(anchor, iend - anchor)) return -1;
    return op - dst;
}

// returns decompressed length or -1
static int64_t snappy_decompress_fb(const uint8_t* src, int64_t srcLen,
                          uint8_t* dst, int64_t dstCap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + srcLen;
    // uvarint
    uint64_t expected = 0;
    int shift = 0;
    while (true) {
        if (ip >= iend || shift > 63) return -1;
        uint8_t b = *ip++;
        expected |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if ((int64_t)expected > dstCap) return -1;
    uint8_t* op = dst;
    uint8_t* oend = dst + dstCap;

    while (ip < iend) {
        uint8_t tag = *ip++;
        if ((tag & 3) == 0) {
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int nb = (int)len - 60;
                if (ip + nb > iend) return -1;
                len = 0;
                for (int i = 0; i < nb; i++) len |= (int64_t)ip[i] << (8 * i);
                len += 1;
                ip += nb;
            }
            if (ip + len > iend || op + len > oend) return -1;
            memcpy(op, ip, len);
            ip += len;
            op += len;
        } else {
            int64_t len, offset;
            if ((tag & 3) == 1) {
                if (ip >= iend) return -1;
                len = 4 + ((tag >> 2) & 7);
                offset = ((int64_t)(tag >> 5) << 8) | *ip++;
            } else if ((tag & 3) == 2) {
                if (ip + 2 > iend) return -1;
                len = (tag >> 2) + 1;
                offset = ip[0] | ((int64_t)ip[1] << 8);
                ip += 2;
            } else {
                if (ip + 4 > iend) return -1;
                len = (tag >> 2) + 1;
                offset = ip[0] | ((int64_t)ip[1] << 8) |
                         ((int64_t)ip[2] << 16) | ((int64_t)ip[3] << 24);
                ip += 4;
            }
            if (offset == 0 || offset > op - dst || op + len > oend) return -1;
            const uint8_t* match = op - offset;
            for (int64_t i = 0; i < len; i++) op[i] = match[i];
            op += len;
        }
    }
    if ((uint64_t)(op - dst) != expected) return -1;
    return op - dst;
}

// --------------------------------------------------------------- batch ----
// Compress/decompress n chunks in one call. srcs/dsts are packed buffers;
// offsets are n+1 prefix arrays. Per-chunk results (compressed sizes) land
// in outSizes; returns 0 or -1 (first failure aborts).

typedef int64_t (*codec_fn)(const uint8_t*, int64_t, uint8_t*, int64_t);


// --------------------------------------------------- byte transpose ------
// R x C byte-matrix transpose (dst[c*R + r] = src[r*C + c]) used by the
// lane byte-plane shuffle (write path) and unshuffle (read path). SSE2
// 16x16 kernel: four unpack stages leave rows in 4-bit bit-reversed
// order (self-inverse), so each vector stores to row BITREV4 of its
// index. ~5x the scalar tiled loop on this host.
#if defined(__SSE2__)
#include <emmintrin.h>
static const int TR16_PERM[16] =
    {0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15};
static inline void tr16x16(__m128i x[16]) {
    __m128i t[16], u[16];
    for (int i = 0; i < 8; ++i) {
        t[i]   = _mm_unpacklo_epi8(x[2*i], x[2*i+1]);
        t[i+8] = _mm_unpackhi_epi8(x[2*i], x[2*i+1]);
    }
    for (int i = 0; i < 8; ++i) {
        u[i]   = _mm_unpacklo_epi16(t[2*i], t[2*i+1]);
        u[i+8] = _mm_unpackhi_epi16(t[2*i], t[2*i+1]);
    }
    for (int i = 0; i < 8; ++i) {
        t[i]   = _mm_unpacklo_epi32(u[2*i], u[2*i+1]);
        t[i+8] = _mm_unpackhi_epi32(u[2*i], u[2*i+1]);
    }
    for (int i = 0; i < 8; ++i) {
        x[i]   = _mm_unpacklo_epi64(t[2*i], t[2*i+1]);
        x[i+8] = _mm_unpackhi_epi64(t[2*i], t[2*i+1]);
    }
}
#endif

static void byte_transpose(const uint8_t* src, int64_t R, int64_t C,
                           uint8_t* dst) {
#if defined(__SSE2__)
    int64_t r0 = 0;
    for (; r0 + 16 <= R; r0 += 16) {
        int64_t c0 = 0;
        for (; c0 + 16 <= C; c0 += 16) {
            __m128i x[16];
            for (int i = 0; i < 16; i++)
                x[i] = _mm_loadu_si128(
                    (const __m128i*)(src + (r0 + i) * C + c0));
            tr16x16(x);
            for (int i = 0; i < 16; i++)
                _mm_storeu_si128(
                    (__m128i*)(dst + (c0 + TR16_PERM[i]) * R + r0), x[i]);
        }
        for (; c0 < C; c0++) {
            uint8_t* d = dst + c0 * R + r0;
            const uint8_t* s = src + r0 * C + c0;
            for (int i = 0; i < 16; i++) { d[i] = *s; s += C; }
        }
    }
    for (; r0 < R; r0++)
        for (int64_t c = 0; c < C; c++)
            dst[c * R + r0] = src[r0 * C + c];
#else
    const int64_t TR = 256;       // cache-tiled scalar fallback
    for (int64_t t0 = 0; t0 < R; t0 += TR) {
        int64_t t1 = t0 + TR < R ? t0 + TR : R;
        for (int64_t c = 0; c < C; c++) {
            uint8_t* d = dst + c * R + t0;
            const uint8_t* s = src + t0 * C + c;
            for (int64_t r = t0; r < t1; r++) { *d++ = *s; s += C; }
        }
    }
#endif
}

// ---- system-library fast paths ------------------------------------
// Block formats are fixed public formats, so the system libraries
// (lz4 1.9 SIMD-tuned, snappy-c) read/write bit-compatible blocks.
// COMPRESSION no longer defers to liblz4: the encoder is the
// deterministic policy above, because the device compressor must
// reproduce its exact bytes and liblz4's hash-table output is not a
// policy anyone else can replay. DECOMPRESSION keeps the syslib fast
// path — any valid block decodes to the same bytes regardless of who
// wrote it, so read speed is free. dlopen'd lazily like zstd; the
// first-party decoder stays as the fallback so the build has no hard
// dependency.
static void* p_lz4_d = nullptr;    // LZ4_decompress_safe
static pthread_once_t lz4_once = PTHREAD_ONCE_INIT;
static void lz4_resolve_once() {
    void* h = dlopen("liblz4.so.1", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("liblz4.so", RTLD_NOW | RTLD_GLOBAL);
    if (!h) return;
    p_lz4_d = dlsym(h, "LZ4_decompress_safe");
}
typedef int (*lz4_d_fn)(const char*, char*, int, int);

int64_t lz4_compress(const uint8_t* src, int64_t srcLen,
                     uint8_t* dst, int64_t dstCap) {
    return lz4_compress_policy(src, srcLen, dst, dstCap);
}

int64_t lz4_decompress(const uint8_t* src, int64_t srcLen,
                       uint8_t* dst, int64_t dstCap) {
    pthread_once(&lz4_once, lz4_resolve_once);
    if (p_lz4_d && srcLen > 0 && srcLen < (1 << 30)
        && dstCap < (1 << 30)) {
        int r = ((lz4_d_fn)p_lz4_d)((const char*)src, (char*)dst,
                                    (int)srcLen, (int)dstCap);
        return r >= 0 ? (int64_t)r : -1;
    }
    return lz4_decompress_fb(src, srcLen, dst, dstCap);
}

static void* p_snp_c = nullptr;    // snappy_compress (snappy-c API)
static void* p_snp_d = nullptr;    // snappy_uncompress
static pthread_once_t snp_once = PTHREAD_ONCE_INIT;
static void snp_resolve_once() {
    void* h = dlopen("libsnappy.so.1", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("libsnappy.so", RTLD_NOW | RTLD_GLOBAL);
    if (!h) return;
    p_snp_c = dlsym(h, "snappy_compress");
    p_snp_d = dlsym(h, "snappy_uncompress");
    if (!p_snp_c || !p_snp_d) { p_snp_c = p_snp_d = nullptr; }
}
typedef int (*snp_fn)(const char*, size_t, char*, size_t*);

int64_t snappy_compress(const uint8_t* src, int64_t srcLen,
                        uint8_t* dst, int64_t dstCap) {
    pthread_once(&snp_once, snp_resolve_once);
    if (p_snp_c) {
        size_t outLen = (size_t)dstCap;
        int s = ((snp_fn)p_snp_c)((const char*)src, (size_t)srcLen,
                                  (char*)dst, &outLen);
        return s == 0 ? (int64_t)outLen : -1;
    }
    return snappy_compress_fb(src, srcLen, dst, dstCap);
}

int64_t snappy_decompress(const uint8_t* src, int64_t srcLen,
                          uint8_t* dst, int64_t dstCap) {
    pthread_once(&snp_once, snp_resolve_once);
    if (p_snp_d) {
        size_t outLen = (size_t)dstCap;
        int s = ((snp_fn)p_snp_d)((const char*)src, (size_t)srcLen,
                                  (char*)dst, &outLen);
        return s == 0 ? (int64_t)outLen : -1;
    }
    return snappy_decompress_fb(src, srcLen, dst, dstCap);
}


static int64_t run_batch(codec_fn fn, const uint8_t* src,
                         const int64_t* srcOffs, uint8_t* dst,
                         const int64_t* dstOffs, int64_t* outSizes,
                         int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t r = fn(src + srcOffs[i], srcOffs[i + 1] - srcOffs[i],
                       dst + dstOffs[i], dstOffs[i + 1] - dstOffs[i]);
        if (r < 0) return -1;
        outSizes[i] = r;
    }
    return 0;
}

int64_t lz4_compress_batch(const uint8_t* src, const int64_t* srcOffs,
                           uint8_t* dst, const int64_t* dstOffs,
                           int64_t* outSizes, int64_t n) {
    return run_batch(lz4_compress, src, srcOffs, dst, dstOffs, outSizes, n);
}

int64_t lz4_decompress_batch(const uint8_t* src, const int64_t* srcOffs,
                             uint8_t* dst, const int64_t* dstOffs,
                             int64_t* outSizes, int64_t n) {
    return run_batch(lz4_decompress, src, srcOffs, dst, dstOffs, outSizes, n);
}

int64_t snappy_compress_batch(const uint8_t* src, const int64_t* srcOffs,
                              uint8_t* dst, const int64_t* dstOffs,
                              int64_t* outSizes, int64_t n) {
    return run_batch(snappy_compress, src, srcOffs, dst, dstOffs, outSizes, n);
}

int64_t snappy_decompress_batch(const uint8_t* src, const int64_t* srcOffs,
                                uint8_t* dst, const int64_t* dstOffs,
                                int64_t* outSizes, int64_t n) {
    return run_batch(snappy_decompress, src, srcOffs, dst, dstOffs, outSizes, n);
}

// ----------------------------------------------------------------- iov ----
// Zero-copy variant: each chunk arrives as its own (pointer, length) pair
// instead of a packed buffer, so Python can hand numpy array views over
// directly — no b"".join / from_buffer_copy staging of ~100MB per
// compaction on the write path.

static int64_t run_iov(codec_fn fn, const uint8_t** srcs,
                       const int64_t* srcLens, uint8_t* dst,
                       const int64_t* dstOffs, int64_t* outSizes,
                       int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t r = fn(srcs[i], srcLens[i], dst + dstOffs[i],
                       dstOffs[i + 1] - dstOffs[i]);
        if (r < 0) return -1;
        outSizes[i] = r;
    }
    return 0;
}

int64_t lz4_compress_iov(const uint8_t** srcs, const int64_t* srcLens,
                         uint8_t* dst, const int64_t* dstOffs,
                         int64_t* outSizes, int64_t n) {
    return run_iov(lz4_compress, srcs, srcLens, dst, dstOffs, outSizes, n);
}

int64_t snappy_compress_iov(const uint8_t** srcs, const int64_t* srcLens,
                            uint8_t* dst, const int64_t* dstOffs,
                            int64_t* outSizes, int64_t n) {
    return run_iov(snappy_compress, srcs, srcLens, dst, dstOffs, outSizes,
                   n);
}

// decompress into caller-provided destinations (one per chunk): reads
// land directly in the numpy arrays the CellBatch will own. Chunks are
// addressed by explicit (offset, length) pairs so raw-stored blocks can
// be skipped without repacking the source.
int64_t lz4_decompress_iov(const uint8_t* src, const int64_t* srcOffs,
                           const int64_t* srcLens, uint8_t** dsts,
                           const int64_t* dstLens, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t r = lz4_decompress(src + srcOffs[i], srcLens[i],
                                   dsts[i], dstLens[i]);
        if (r != dstLens[i]) return -1;
    }
    return 0;
}

int64_t snappy_decompress_iov(const uint8_t* src, const int64_t* srcOffs,
                              const int64_t* srcLens, uint8_t** dsts,
                              const int64_t* dstLens, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t r = snappy_decompress(src + srcOffs[i], srcLens[i],
                                      dsts[i], dstLens[i]);
        if (r != dstLens[i]) return -1;
    }
    return 0;
}

// ---------------------------------------------------------------- zstd ----
// Zstd rides the system libzstd (dlopen'd lazily — the reference links
// zstd-jni the same way: a thin binding over the real library). The
// symbols used are the stable simple API only.

typedef size_t (*ZSTD_compress_t)(void*, size_t, const void*, size_t, int);
typedef size_t (*ZSTD_decompress_t)(void*, size_t, const void*, size_t);
typedef size_t (*ZSTD_compressBound_t)(size_t);
typedef unsigned (*ZSTD_isError_t)(size_t);

static ZSTD_compress_t p_zstd_compress = nullptr;
static ZSTD_decompress_t p_zstd_decompress = nullptr;
static ZSTD_compressBound_t p_zstd_bound = nullptr;
static ZSTD_isError_t p_zstd_iserr = nullptr;
static int zstd_state = 0;  // 0 unresolved, 1 ok, -1 unavailable

// first zstd call can come concurrently from a flush writer and a
// compaction reader — the one-time dlopen/dlsym must not race
static pthread_once_t zstd_once = PTHREAD_ONCE_INIT;

static void zstd_resolve_once() {
    void* h = dlopen("libzstd.so.1", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("libzstd.so", RTLD_NOW | RTLD_GLOBAL);
    if (h) {
        p_zstd_compress = (ZSTD_compress_t)dlsym(h, "ZSTD_compress");
        p_zstd_decompress = (ZSTD_decompress_t)dlsym(h, "ZSTD_decompress");
        p_zstd_bound = (ZSTD_compressBound_t)dlsym(h, "ZSTD_compressBound");
        p_zstd_iserr = (ZSTD_isError_t)dlsym(h, "ZSTD_isError");
    }
    zstd_state = (p_zstd_compress && p_zstd_decompress && p_zstd_bound &&
                  p_zstd_iserr) ? 1 : -1;
}

static int zstd_resolve() {
    pthread_once(&zstd_once, zstd_resolve_once);
    return zstd_state;
}

int64_t zstd_available() { return zstd_resolve() == 1 ? 1 : 0; }

int64_t zstd_max_compressed(int64_t n) {
    if (zstd_resolve() != 1) return -1;
    return (int64_t)p_zstd_bound((size_t)n);
}

// THREAD-LOCAL: each caller sets its level immediately before its codec
// calls (same thread), so instances with different levels never clobber
// each other and there is no cross-thread race on the level
static thread_local int g_zstd_level = 3;
void zstd_set_level(int level) { g_zstd_level = level; }

int64_t zstd_compress(const uint8_t* src, int64_t srcLen,
                      uint8_t* dst, int64_t dstCap) {
    if (zstd_resolve() != 1) return -1;
    size_t r = p_zstd_compress(dst, (size_t)dstCap, src, (size_t)srcLen,
                               g_zstd_level);
    if (p_zstd_iserr(r)) return -1;
    return (int64_t)r;
}

int64_t zstd_decompress(const uint8_t* src, int64_t srcLen,
                        uint8_t* dst, int64_t dstCap) {
    if (zstd_resolve() != 1) return -1;
    size_t r = p_zstd_decompress(dst, (size_t)dstCap, src, (size_t)srcLen);
    if (p_zstd_iserr(r)) return -1;
    return (int64_t)r;
}

int64_t zstd_compress_batch(const uint8_t* src, const int64_t* srcOffs,
                            uint8_t* dst, const int64_t* dstOffs,
                            int64_t* outSizes, int64_t n) {
    return run_batch(zstd_compress, src, srcOffs, dst, dstOffs, outSizes, n);
}

int64_t zstd_decompress_batch(const uint8_t* src, const int64_t* srcOffs,
                              uint8_t* dst, const int64_t* dstOffs,
                              int64_t* outSizes, int64_t n) {
    return run_batch(zstd_decompress, src, srcOffs, dst, dstOffs, outSizes,
                     n);
}

int64_t zstd_compress_iov(const uint8_t** srcs, const int64_t* srcLens,
                          uint8_t* dst, const int64_t* dstOffs,
                          int64_t* outSizes, int64_t n) {
    return run_iov(zstd_compress, srcs, srcLens, dst, dstOffs, outSizes, n);
}

int64_t zstd_decompress_iov(const uint8_t* src, const int64_t* srcOffs,
                            const int64_t* srcLens, uint8_t** dsts,
                            const int64_t* dstLens, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t r = zstd_decompress(src + srcOffs[i], srcLens[i],
                                    dsts[i], dstLens[i]);
        if (r != dstLens[i]) return -1;
    }
    return 0;
}

// -------------------------------------------------------- segment pack ----
// The fused write-path entry point: one GIL-released FFI call per segment
// does (optional) lane delta-transform + order check, per-block
// compress-or-store-raw, CRC32, and a sequential copy into `out` — the
// role of the reference's CompressedSequentialWriter.flushData chain
// (io/compress/CompressedSequentialWriter.java:140-205) without
// re-entering Python per block.
//
//   codec: 0 noop, 1 lz4, 2 snappy, 3 zstd
//   blocks/lens: nblocks source buffers
//   attempt[i]: 0 => store raw without trying (caller's skip heuristic)
//   maxCompressedLen: min_compress_ratio fallback bound
//   shuffle_block: index of the block to byte-plane-shuffle as
//                  u32[lane_width] rows (-1 = none); scratch must hold
//                  that block. Measured on real lane data: the plane
//                  layout compresses better AND 1.2-3x faster than
//                  row-major for lz4 and zstd both (blosc's shuffle
//                  filter, applied to the identity-lane matrix). Rows
//                  are also lex order-checked (u32 numeric per column)
//                  while shuffling — the writer's out-of-order guard.
//   out/outCap: destination; blocks land back to back
//   outSizes/outRaw/outCrcs: per-block stored size, raw?, crc32
// Returns total bytes placed in out; -1 codec/capacity error; -3 order
// violation inside the shuffled block.

int64_t segment_pack(int64_t codec, const uint8_t** blocks,
                     const int64_t* lens, int64_t nblocks,
                     const uint8_t* attempt, int64_t maxCompressedLen,
                     int64_t shuffle_block, int64_t lane_width,
                     uint8_t* scratch, uint8_t* out, int64_t outCap,
                     int64_t* outSizes, uint8_t* outRaw,
                     uint32_t* outCrcs) {
    codec_fn fn = nullptr;
    if (codec == 1) fn = lz4_compress;
    else if (codec == 2) fn = snappy_compress;
    else if (codec == 3) { if (zstd_resolve() != 1) return -1;
                           fn = zstd_compress; }
    int64_t pos = 0;
    for (int64_t i = 0; i < nblocks; i++) {
        const uint8_t* srcp = blocks[i];
        int64_t srcLen = lens[i];
        if (i == shuffle_block && lane_width > 0) {
            int64_t W = 4 * lane_width;          // row bytes
            int64_t nrows = srcLen / W;
            byte_transpose(srcp, nrows, W, scratch);
            // lexicographic order check (u32 numeric per column)
            const uint32_t* rows = (const uint32_t*)srcp;
            for (int64_t r = 1; r < nrows; r++) {
                const uint32_t* prev = rows + (r - 1) * lane_width;
                const uint32_t* cur = rows + r * lane_width;
                for (int64_t c = 0; c < lane_width; c++) {
                    if (cur[c] != prev[c]) {
                        if (cur[c] < prev[c]) return -3;
                        break;
                    }
                }
            }
            srcp = scratch;
        }
        int64_t stored;
        int raw = 1;
        if (fn && attempt[i]) {
            // compress straight into out; cap at the raw length (worse
            // than raw => store raw) and the min_compress_ratio bound
            int64_t cap = srcLen < maxCompressedLen ? srcLen
                                                    : maxCompressedLen;
            if (cap > outCap - pos) cap = outCap - pos;
            int64_t r = fn(srcp, srcLen, out + pos, cap);
            if (r >= 0 && r < srcLen && r < maxCompressedLen) {
                stored = r;
                raw = 0;
            } else {
                stored = srcLen;
            }
        } else {
            stored = srcLen;
        }
        if (raw) {
            if (srcLen > outCap - pos) return -1;
            memcpy(out + pos, srcp, srcLen);
            stored = srcLen;
        }
        outSizes[i] = stored;
        outRaw[i] = (uint8_t)raw;
        outCrcs[i] = (uint32_t)crc32(0, out + pos, (uInt)stored);
        pos += stored;
    }
    return pos;
}

// Reader side of segment_pack's shuffle: byte planes -> row-major.
// planes holds W*nrows bytes (W = 4*lane_width); rows receives the
// [nrows, lane_width] u32 matrix. W sequential read streams, one
// sequential write stream.
void lanes_unshuffle(const uint8_t* planes, uint8_t* rows, int64_t nrows,
                     int64_t lane_width) {
    byte_transpose(planes, 4 * lane_width, nrows, rows);
}


// Partition boundaries: indices where the first 4 identity lanes (the
// partition key lanes) change. One cache-friendly pass replacing the
// writer's strided numpy slice-copy + row compare. Returns the count.
int64_t part_boundaries(const uint32_t* lanes, int64_t nrows,
                        int64_t lane_width, int64_t* out_idx) {
    if (nrows == 0) return 0;
    int64_t n = 0;
    out_idx[n++] = 0;
    const uint32_t* prev = lanes;
    const uint32_t* cur = lanes + lane_width;
    for (int64_t r = 1; r < nrows; r++) {
        if (cur[0] != prev[0] || cur[1] != prev[1] ||
            cur[2] != prev[2] || cur[3] != prev[3])
            out_idx[n++] = r;
        prev = cur;
        cur += lane_width;
    }
    return n;
}

// ------------------------------------------------------------ gather -----
// Permuted ragged-frame gather: out[new_off[i] .. new_off[i+1]) =
// payload[off[perm[i]] .. off[perm[i]+1]). The CellBatch payload shuffle is
// the host-side hot loop of compaction (numpy's fancy indexing builds a
// per-byte index array; this is a straight memcpy per frame).

int64_t gather_frames(const uint8_t* payload, const int64_t* off,
                      const int64_t* perm, int64_t n,
                      const int64_t* new_off, uint8_t* out) {
    for (int64_t i = 0; i < n; i++) {
        int64_t j = perm[i];
        int64_t len = off[j + 1] - off[j];
        if (len != new_off[i + 1] - new_off[i]) return -1;
        memcpy(out + new_off[i], payload + off[j], len);
    }
    return 0;
}

}  // extern "C"
