"""TLS for the internode and native-protocol transports.

Reference counterpart: security/SSLFactory.java driven by
conf/cassandra.yaml `server_encryption_options` (internode, mutual TLS
against the cluster CA) and `client_encryption_options` (native
protocol: server cert, optionally required client certs). Contexts are
built once per transport; python's ssl module does the wire work.

Internode peers dial each other by address, so hostname checking is
off and trust roots at the CLUSTER CA instead — only certificates the
operator signed can join, which is the property internode TLS exists
to enforce (encryption + peer authentication, not DNS identity).
"""
from __future__ import annotations

import ssl
from dataclasses import dataclass


@dataclass
class TLSConfig:
    certfile: str
    keyfile: str
    cafile: str | None = None
    require_client_auth: bool = True   # mutual TLS (internode default)

    def __post_init__(self):
        if self.require_client_auth and not self.cafile:
            # refusing to build a half-configured trust story: without
            # a CA, "require client auth" would silently verify nothing
            # and any TLS speaker could join the cluster
            raise ValueError(
                "require_client_auth needs cafile (the cluster CA); "
                "pass require_client_auth=False for encryption-only")

    @classmethod
    def from_dict(cls, d: dict | None) -> "TLSConfig | None":
        if not d:
            return None
        return cls(d["certfile"], d["keyfile"], d.get("cafile"),
                   bool(d.get("require_client_auth", True)))

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        if self.require_client_auth:
            ctx.load_verify_locations(self.cafile)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def client_context(self) -> ssl.SSLContext:
        return client_side_context(self.cafile, self.certfile,
                                   self.keyfile)


def client_side_context(cafile: str | None = None,
                        certfile: str | None = None,
                        keyfile: str | None = None) -> ssl.SSLContext:
    """The ONE outbound-TLS context builder — internode dialers and the
    native-protocol driver both come through here, so hardening (min
    version, ciphers) lands in both. Verifies the server against
    `cafile` (trust-all when omitted — lab default for the driver;
    internode configs always carry a CA via TLSConfig validation) and
    presents a client cert only if given."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    if cafile:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cafile)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if certfile:
        ctx.load_cert_chain(certfile, keyfile)
    return ctx
