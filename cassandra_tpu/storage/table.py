"""ColumnFamilyStore equivalent: per-table store owning the memtable, the
live SSTable set, and the flush machinery.

Reference counterpart: db/ColumnFamilyStore.java (switchMemtable:1038,
inner Flush:1180, forceFlush:1089), db/lifecycle/Tracker.java:85 (the
atomic view of live memtables+sstables).
"""
from __future__ import annotations

import os
import threading
from ..utils import lockwitness
import time

import numpy as np

from ..schema import TableMetadata
from ..utils import timeutil
from .cellbatch import (FLAG_PARTITION_DEL, CellBatch, merge_sorted,
                        truncate_live_rows)
from .commitlog import write_fastpath_enabled
from .failures import (FailureHandler, list_quarantined,
                       quarantine_descriptor_files)
from .memtable import Memtable
from .mutation import Mutation
from .row_cache import RowCache
from .sstable import Descriptor, SSTableReader, SSTableWriter
from .sstable.reader import CorruptSSTableError


def read_fastpath_enabled() -> bool:
    """CTPU_READ_FASTPATH=0 disables timestamp-skip collation and batched
    partition reads for A/B runs (bench.py read section,
    scripts/check_readpath_ab.py). Read per call so a toggle mid-process
    takes effect immediately."""
    return os.environ.get("CTPU_READ_FASTPATH", "1") != "0"


def _partition_deletion_ts(batch: CellBatch) -> int | None:
    """Timestamp of the newest partition-scope deletion in a source's
    view of one partition (None when it has none) — the accumulator the
    timestamp-skip rule compares remaining sstables against."""
    mask = (batch.flags & FLAG_PARTITION_DEL) != 0
    if not mask.any():
        return None
    return int(batch.ts[mask].max())


class WriteBarrier:
    """The OpOrder role (utils/concurrent/OpOrder.java, used by the
    reference's Flush at db/ColumnFamilyStore.java:1180-1240): writers
    enter in SHARED mode — concurrently; the commitlog segment lock and
    the memtable shard locks provide the fine-grained exclusion — while
    the memtable switch enters EXCLUSIVE, so every write lands atomically
    on one side of the flush point. Exclusive-preferring: a pending
    switch blocks new shared entries, so flush cannot starve. NOT
    reentrant in either mode."""

    __slots__ = ("_cond", "_shared", "_excl", "_excl_waiting")

    def __init__(self):
        self._cond = lockwitness.make_condition("table.write_barrier")
        self._shared = 0
        self._excl = False
        self._excl_waiting = 0

    def shared(self):
        return _SharedEntry(self)

    def exclusive(self):
        return _ExclusiveEntry(self)


class _SharedEntry:
    __slots__ = ("_b",)

    def __init__(self, b):
        self._b = b

    def __enter__(self):
        b = self._b
        with b._cond:
            while b._excl or b._excl_waiting:
                b._cond.wait()
            b._shared += 1
        return self

    def __exit__(self, *exc):
        b = self._b
        with b._cond:
            b._shared -= 1
            if b._shared == 0:
                b._cond.notify_all()


class _ExclusiveEntry:
    __slots__ = ("_b",)

    def __init__(self, b):
        self._b = b

    def __enter__(self):
        b = self._b
        with b._cond:
            b._excl_waiting += 1
            try:
                while b._excl or b._shared:
                    b._cond.wait()
            finally:
                b._excl_waiting -= 1
            b._excl = True
        return self

    def __exit__(self, *exc):
        b = self._b
        with b._cond:
            b._excl = False
            b._cond.notify_all()


class Tracker:
    """Atomic view of the live data sources (db/lifecycle/Tracker.java:85).
    Mutated under a lock; readers grab a consistent snapshot list."""

    def __init__(self):
        self._lock = lockwitness.make_rlock("table.tracker")
        self.sstables: list[SSTableReader] = []
        self._by_max_ts: list[SSTableReader] | None = None

    def view(self) -> list[SSTableReader]:
        with self._lock:
            return list(self.sstables)

    def view_by_max_ts(self) -> list[SSTableReader]:
        """max_ts-DESCENDING snapshot for the read fast lane, memoized —
        the ordering only changes when the sstable set does, and the
        per-read sort was measurable on the path being optimized."""
        with self._lock:
            if self._by_max_ts is None:
                self._by_max_ts = sorted(self.sstables,
                                         key=lambda r: r.max_ts,
                                         reverse=True)
            return list(self._by_max_ts)

    def add(self, reader: SSTableReader) -> None:
        with self._lock:
            self.sstables.append(reader)
            self.sstables.sort(key=lambda r: r.desc.generation)
            self._by_max_ts = None

    def replace(self, removed: list[SSTableReader],
                added: list[SSTableReader]) -> None:
        with self._lock:
            keep = [s for s in self.sstables if s not in removed]
            self.sstables = sorted(keep + added,
                                   key=lambda r: r.desc.generation)
            self._by_max_ts = None


class ColumnFamilyStore:
    DEFAULT_FLUSH_THRESHOLD = 64 * 1024 * 1024  # bytes of live memtable data

    def __init__(self, table: TableMetadata, data_dir: str,
                 commitlog=None, flush_threshold: int | None = None,
                 memtable_shards: int | None = None,
                 failures: FailureHandler | None = None):
        self.table = table
        # disk/commit failure policy decisions (FSErrorHandler role);
        # engine-scoped when opened by a StorageEngine, a private
        # best_effort/ignore default for standalone stores
        self.failures = failures or FailureHandler()
        self.memtable_shards = memtable_shards
        self.directory = os.path.join(
            data_dir, table.keyspace,
            f"{table.name}-{table.id.hex[:8]}")
        os.makedirs(self.directory, exist_ok=True)
        self.commitlog = commitlog
        self.flush_threshold = flush_threshold or self.DEFAULT_FLUSH_THRESHOLD
        self.tracker = Tracker()
        self.memtable = Memtable(table, shards=memtable_shards)
        self._flush_lock = lockwitness.make_lock("table.flush")
        # write barrier (OpOrder role): writers shared, switch exclusive
        self._barrier = WriteBarrier()
        # per-table counters (the metrics vtable merges these with the
        # hist groups below). The byte counters are the amplification
        # accounting's single source: bytes_ingested = mutation payload
        # applied to the memtable, bytes_flushed = flush outputs,
        # bytes_compacted_in/out = compaction task input/output sizes
        # (compaction/task.py folds them from the same stats it appends
        # to compaction_history) — amplification() derives WA from
        # exactly these, so every surface reconciles arithmetically.
        self.metrics = {"writes": 0, "reads": 0, "flushes": 0,
                        "bytes_flushed": 0, "bytes_ingested": 0,
                        "bytes_compacted_in": 0, "bytes_compacted_out": 0}
        # per-table latency group (TableMetrics role): decaying
        # read/write latency hists under table.<ks>.<name>.* — counters
        # stay in the plain dict above (the metrics vtable merges both).
        # Hists are resolved ONCE: the hot paths touch only the per-hist
        # lock, never the global registry lock.
        from ..service.metrics import GLOBAL as _METRICS
        self.latency = _METRICS.group(
            f"table.{table.keyspace}.{table.name}")
        self.read_hist = self.latency.hist("read_latency")
        self.write_hist = self.latency.hist("write_latency")
        # sstables consulted per point read (TableMetrics
        # sstablesPerReadHistogram role) — the observable proof that
        # timestamp-skip collation is actually skipping
        self.sstables_per_read = self.latency.hist("sstables_per_read")
        self.multiread_hist = self.latency.hist("multiread_latency")
        # corrupt-sstable quarantine (the reference's markSuspect +
        # JVMStabilityInspector routing): records survive restarts via
        # the on-disk quarantine/ directory
        self._quarantine_lock = lockwitness.make_lock("table.quarantine")
        self.quarantined: list[dict] = list_quarantined(self.directory)
        from .lifecycle import replay_directory
        replay_directory(self.directory)
        for desc in Descriptor.list_in(self.directory):
            try:
                self.tracker.add(SSTableReader(desc, self.table))
            except (CorruptSSTableError, OSError) as e:
                # a corrupt sstable must not abort store OPEN: route the
                # error through the policy; best_effort quarantines the
                # files and the store comes up without them
                policy = self.failures.handle(e, desc.path("Data.db"))
                if policy == "best_effort":
                    self._quarantine_descriptor(desc, e)
                else:
                    raise
        self.compaction_listener = None  # set by CompactionManager
        # per-compaction stats ring (system_views.compaction_history /
        # nodetool compactionhistory), newest kept: bounded by the
        # mutable compaction_history_entries knob (a StorageEngine
        # rebinds on knob change; standalone stores read the config
        # default) — the engine-lifetime unbounded list this replaced
        # grew one dict per compaction forever
        from collections import deque as _deque

        from ..config import Config as _ConfigDefaults
        # class-attribute read: the dataclass default, no throwaway
        # Config() instance per store (a StorageEngine rebinds from
        # live settings right after open)
        self.compaction_history: _deque = _deque(
            maxlen=self._history_maxlen(
                _ConfigDefaults.compaction_history_entries))
        self._comp_hist_lock = lockwitness.make_lock(
            "table.comp_history")
        # space-amplification estimate cached per live generation set
        # (the _mesh_bounds_cache pattern): the token-union walk is
        # O(P log P) and only changes when the sstable set does
        self._sa_cache: tuple | None = None
        # mesh routing width: a StorageEngine points this at ITS
        # compaction_mesh_devices knob (the fanout pool is process-
        # global, sized to the max across engines — a co-hosted
        # engine's knob must not flip this store's data plane); a
        # standalone store follows the anonymous process demand
        from ..parallel import fanout as _fanout_mod
        self.mesh_devices_fn = _fanout_mod.mesh_devices
        # decode-ahead routing mirrors the mesh knob: a StorageEngine
        # points this at ITS `compaction_decode_ahead` setting; a
        # standalone store reads the knob's config DEFAULT (so a
        # default change propagates here without a second edit)
        from ..config import Config as _Config
        self.decode_ahead_fn = \
            lambda: bool(_Config().compaction_decode_ahead)
        # device-side block compression routing follows the same shape:
        # a StorageEngine points this at ITS hot-reloadable
        # `compaction_device_compress` setting; a standalone store
        # reads the config default
        self.device_compress_fn = \
            lambda: bool(_Config().compaction_device_compress)
        # analytical-scan device kernel routing, same shape again: a
        # StorageEngine points this at ITS hot-reloadable
        # `scan_device_filter` setting; scan_filtered re-reads it PER
        # SEGMENT (results identical either way)
        self.scan_device_filter_fn = \
            lambda: bool(_Config().scan_device_filter)
        # eager attached-index builds: a StorageEngine points this at
        # IndexManager.build_eager so new sstables (flush/compaction)
        # get their index components in the writer tail; a standalone
        # store has no index registry to feed
        self.index_build_fn = None
        # planned mesh boundaries, keyed (live generations, n_shards):
        # planning walks every live sstable's partition directory
        # (O(P log P) in total partitions) and only changes when the
        # sstable set does — one cached plan per live view
        self._mesh_bounds_cache: tuple | None = None
        # the row-cache store key is the data directory: unique per
        # store, so in-process multi-node clusters never cross-serve
        self.row_cache = RowCache(self.directory) \
            if table.params.caching.get(
                "rows_per_partition", "NONE") != "NONE" else None
        if self.row_cache is not None:
            # entries surviving from a previous in-process store over
            # this directory predate whatever happened to it since
            self.row_cache.clear()
        self._gen_lock = lockwitness.make_lock("table.gen")
        # quarantined generations count too: their files left the live
        # directory, and a restart re-minting one of their numbers
        # would make the quarantine records misreport the new sstable
        # (and its dedupe block a future quarantine of it)
        self._last_gen = max(
            [d.generation for d in Descriptor.list_in(self.directory)]
            + [q["generation"] for q in self.quarantined],
            default=0)

    @staticmethod
    def _history_maxlen(n) -> int | None:
        """compaction_history_entries knob → deque maxlen (<= 0 means
        unbounded, the pre-bound behavior)."""
        n = int(n)
        return n if n > 0 else None

    def set_compaction_history_capacity(self, n) -> None:
        """Hot-apply the mutable compaction_history_entries knob:
        rebuild the ring at the new bound, NEWEST entries kept (deque
        maxlen cannot be resized in place). The swap and the task-side
        append (record_compaction) share a lock — a compaction
        finishing mid-hot-set must not land its entry on the discarded
        ring."""
        from collections import deque as _deque
        maxlen = self._history_maxlen(n)
        with self._comp_hist_lock:
            self.compaction_history = _deque(self.compaction_history,
                                             maxlen=maxlen)

    def record_compaction(self, stats: dict) -> None:
        """Fold one finished compaction into the store's observability
        state: the bounded history ring (under the swap lock) and the
        monotonic amplification counters, which survive ring
        eviction."""
        with self._comp_hist_lock:
            self.compaction_history.append(stats)
        self.metrics["bytes_compacted_in"] = \
            self.metrics.get("bytes_compacted_in", 0) \
            + stats.get("bytes_read", 0)
        self.metrics["bytes_compacted_out"] = \
            self.metrics.get("bytes_compacted_out", 0) \
            + stats.get("bytes_written", 0)

    # ------------------------------------------------------ amplification --

    def amplification(self) -> dict:
        """Observed per-table write/space amplification — the signals
        the adaptive-compaction loop (ROADMAP item 4) tunes by, derived
        from the SAME counters every other surface reports so bench /
        check_observatory can reconcile them arithmetically:

        - write_amplification = (bytes_flushed + bytes_compacted_out)
          / bytes_ingested — physical bytes written per logical byte
          the memtable absorbed (the RocksDB-style W-Amp; 0.0 until
          anything was ingested).
        - space_amplification = total live partition INSTANCES /
          distinct live partitions across the sstable set's partition
          directories (token arrays already resident — no decode). A
          fully-compacted table reads 1.0; N overlapping copies of the
          same keys read ≈ N. This is the live-vs-logical size ratio
          in partition units, the overlap signal `sstables_per_read`
          measures from the read side.
        """
        m = self.metrics
        ingested = m.get("bytes_ingested", 0)
        written = m.get("bytes_flushed", 0) \
            + m.get("bytes_compacted_out", 0)
        wa = (written / ingested) if ingested > 0 else 0.0
        live = self.tracker.view()
        # the O(P log P) token-union walk is cached per live
        # generation set: callers include the history sampler tick and
        # the METRICS_SNAPSHOT handler on the single messaging
        # dispatch worker — neither may pay the sort when the sstable
        # set has not changed
        key = tuple(r.desc.generation for r in live)
        cached = self._sa_cache
        if cached is not None and cached[0] == key:
            sa = cached[1]
        else:
            total_parts = sum(s.n_partitions for s in live)
            if total_parts > 0:
                toks = np.concatenate(
                    [np.asarray(s.partition_tokens)
                     for s in live if s.n_partitions > 0])
                distinct = len(np.unique(toks))
                sa = total_parts / max(distinct, 1)
            else:
                sa = 1.0
            self._sa_cache = (key, sa)
        return {"write_amplification": round(wa, 6),
                "space_amplification": round(sa, 6)}

    def set_compaction_params(self, params: dict) -> dict:
        """Hot-swap the table's compaction params (the ALTER TABLE /
        adaptive-controller actuation seam). `get_strategy` reads
        `table.params.compaction` fresh on every selection, so the NEXT
        selection sees the new strategy; a task already in flight keeps
        its claimed inputs (CompactionManager's claim registry) and
        finishes under the OLD plan — the swap is a single reference
        assignment, never a mutation of the dict a running selection
        might hold. Returns the previous params; notifies the
        compaction listener so the new strategy gets a prompt look at
        the existing sstable set."""
        old = dict(self.table.params.compaction)
        self.table.params.compaction = dict(params)
        if self.compaction_listener:
            self.compaction_listener(self)
        return old

    def reload_sstables(self) -> None:
        """Pick up sstables written into the directory out-of-band
        (bulk load / sstableloader role). NOT safe concurrently with
        in-process flush/compaction — those register their outputs with
        the tracker themselves; calling this mid-write can double-add a
        generation. Quiesce writes first."""
        with self._gen_lock:
            known = {s.desc.generation for s in self.tracker.view()}
            for desc in Descriptor.list_in(self.directory):
                if desc.generation not in known:
                    self.tracker.add(SSTableReader(desc, self.table))
                    self._last_gen = max(self._last_gen, desc.generation)
        if self.row_cache is not None:
            self.row_cache.clear()   # bulk-loaded data changes content

    def next_generation(self) -> int:
        """Race-free generation allocation shared by flush + compaction
        (a directory re-scan alone is a TOCTOU between writers)."""
        with self._gen_lock:
            self._last_gen = max(self._last_gen + 1,
                                 Descriptor.next_generation(self.directory))
            return self._last_gen

    # --------------------------------------------------------- quarantine --

    def _quarantine_descriptor(self, desc, err) -> dict | None:
        """Move one generation's files into quarantine/ and record it.
        Idempotent per generation (concurrent readers hitting the same
        rot race to a single quarantine)."""
        with self._quarantine_lock:
            if any(q["generation"] == desc.generation
                   for q in self.quarantined):
                return None
            entry = quarantine_descriptor_files(desc, reason=repr(err))
            self.quarantined.append(entry)
        return entry

    def quarantine_sstable(self, sst: SSTableReader, err) -> dict | None:
        """Blacklist a corrupt sstable out of the live view: snapshot
        its components into quarantine/ for forensics, drop it from the
        tracker (reads, compaction candidate selection, streaming and
        snapshots all plan from the tracker), and invalidate every
        cache that could still serve its bytes. In-flight reads holding
        the reader finish safely on its open fd (release, not close)."""
        entry = self._quarantine_descriptor(sst.desc, err)
        if entry is None:
            return None
        self.tracker.replace([sst], [])
        sst.release()
        from .chunk_cache import GLOBAL as chunk_cache
        from .key_cache import GLOBAL as key_cache
        chunk_cache.invalidate_generation(sst.desc.directory,
                                          sst.desc.generation)
        key_cache.invalidate_generation(sst.desc.directory,
                                        sst.desc.generation)
        if self.row_cache is not None:
            # cached merges were computed over a source set that
            # included the quarantined sstable
            self.row_cache.clear()
        # diagnostic event + flight-recorder bundle: quarantine is an
        # irreversible decision the black box must have context for
        self.failures.notify_quarantine(
            {**entry, "keyspace": self.table.keyspace,
             "table": self.table.name})
        return entry

    def _degrade_on_corruption(self, sst: SSTableReader,
                               err: BaseException) -> None:
        """One sstable failed mid-read. Route through the disk failure
        policy: best_effort quarantines it and RETURNS so the read
        re-serves from the remaining sources; every other policy
        re-raises (ignore = let the request fail; stop/die have already
        taken the node out of service via the handler)."""
        path = sst.desc.path("Data.db")
        policy = self.failures.handle(err, path)
        if policy != "best_effort":
            raise err
        self.quarantine_sstable(sst, err)

    # ------------------------------------------------------------- write --

    def apply(self, mutation: Mutation, commitlog=None,
              durable: bool = True) -> None:
        """Commitlog append + memtable put as one unit against a single
        memtable epoch (Keyspace.applyInternal ordering). The shared
        side of the write barrier makes every write either fully before
        a flush's switch point (old memtable, CL position < flush
        position) or fully after (new memtable, CL position >= flush
        position) — without serializing writers against each other.
        The commitlog DURABILITY wait happens outside the barrier:
        parked writers must not block the writers coalescing behind
        them (that wait is the group-commit batch forming)."""
        wait_for = None
        with self._barrier.shared():
            if commitlog is not None and durable:
                _pos, wait_for = commitlog.append(mutation)
            self.memtable.apply(mutation)
            self.metrics["writes"] += 1
            self.metrics["bytes_ingested"] += mutation.size
        # invalidate BEFORE the durability wait: the memtable already
        # holds the cells, and a failed sync raising past a stale cache
        # entry would leave cache-hit and memtable reads divergent
        if self.row_cache is not None:
            self.row_cache.invalidate(mutation.pk)
        if wait_for is not None:
            commitlog.await_durable(wait_for)

    def apply_batch(self, mutations: list[Mutation], commitlog=None,
                    durable: bool = True) -> None:
        """Batched apply against ONE memtable epoch: the whole batch is
        commitlog-appended under one lock acquisition + one durability
        barrier (CommitLog.append_batch), then memtable-applied taking
        each token shard's lock once (Memtable.apply_batch). Same
        barrier atomicity as apply()."""
        if not mutations:
            return
        wait_for = None
        with self._barrier.shared():
            if commitlog is not None and durable:
                _poss, wait_for = commitlog.append_batch(mutations)
            self.memtable.apply_batch(mutations)
            self.metrics["writes"] += len(mutations)
            self.metrics["bytes_ingested"] += \
                sum(m.size for m in mutations)
        # invalidation before the durability wait — see apply()
        if self.row_cache is not None:
            for pk in {m.pk for m in mutations}:
                self.row_cache.invalidate(pk)
        if wait_for is not None:
            commitlog.await_durable(wait_for)

    def should_flush(self) -> bool:
        return self.memtable.live_bytes >= self.flush_threshold

    # ------------------------------------------------------------- flush --

    def flush(self) -> SSTableReader | None:
        """Switch the memtable and write it out (ColumnFamilyStore.Flush).
        Returns the new sstable reader (None if memtable was empty).

        Fast lane (CTPU_WRITE_FASTPATH): the retired memtable drains
        SHARD BY SHARD — each shard's drain+sort (numpy, GIL-releasing)
        overlaps the previous shard's serialization, the shared
        compressor pool's parallel compress of earlier segments
        (storage/sstable/compress_pool.py; ordered completion keeps
        bytes identical for any pool size) and the writer I/O thread's
        disk writes — a 4-stage flush pipeline whose output is
        bit-identical to the serial sort-everything-then-write path
        (shards are disjoint ascending token ranges, so per-shard
        sorted runs concatenate in global order; proven by
        scripts/check_writepath_ab.py and check_compaction_ab.py)."""
        with self._flush_lock:
            with self._barrier.exclusive():
                old = self.memtable
                if old.is_empty:
                    return None
                flush_pos = self.commitlog.current_position() \
                    if self.commitlog else None
                self.memtable = Memtable(self.table,
                                     shards=self.memtable_shards)
            fast = write_fastpath_enabled()
            gen = self.next_generation()
            desc = Descriptor(self.directory, gen)
            if fast:
                from .sstable.compress_pool import get_pool
                pool = get_pool()
            else:
                pool = None
            writer = SSTableWriter(
                desc, self.table,
                estimated_partitions=old.partition_count(),
                threaded_io=fast, compress_pool=pool,
                metrics_group="flush")
            try:
                if fast:
                    self._append_pipelined(old, writer)
                else:
                    writer.append(old.flush_batch())
                stats = writer.finish()
                # the read-back is part of the flush: a failure HERE
                # (EIO/corruption re-opening the just-written sstable)
                # must restore the memtable too, or acked writes vanish
                # from reads. abort() after finish() is a no-op on the
                # renamed components — the orphan sstable reconciles
                # away (or quarantines) at the next store open.
                reader = SSTableReader(desc, self.table)
            except BaseException as e:
                writer.abort()
                # a failed flush must not LOSE the memtable: reinstate
                # the retired one as active (absorbing whatever landed
                # in its replacement while the doomed write ran) so the
                # data stays readable and a later flush can retry; the
                # commitlog segments stay dirty (no discard_completed)
                self._restore_memtable(old)
                from ..service import diagnostics
                diagnostics.publish("flush.abort",
                                    keyspace=self.table.keyspace,
                                    table=self.table.name,
                                    error=repr(e))
                if isinstance(e, (OSError, CorruptSSTableError)):
                    self.failures.handle(
                        e, getattr(writer, "_data_path", ""))
                raise
            self.tracker.add(reader)
            if self.index_build_fn is not None:
                # eager attached-index components for the new sstable
                # (build_eager never raises — a failed build falls back
                # to the lazy first-use path, counted)
                self.index_build_fn(reader)
            from ..service import diagnostics
            diagnostics.publish("flush", keyspace=self.table.keyspace,
                                table=self.table.name,
                                generation=gen,
                                cells=stats.get("n_cells", 0),
                                bytes=reader.data_size)
            if self.row_cache is not None:
                # sstable-set change: cached merges must never outlive
                # the generation they were computed from (also closes
                # the switch→tracker.add window where a racing read
                # could have cached a view missing the flushing data)
                self.row_cache.clear()
            if getattr(self, "backup_enabled", lambda: False)():
                self._backup_sstable(desc)
            self.metrics["flushes"] += 1
            self.metrics["bytes_flushed"] += reader.data_size
            if self.commitlog and flush_pos:
                self.commitlog.discard_completed(self.table.id, flush_pos)
            if self.compaction_listener:
                self.compaction_listener(self)
            return reader

    def _restore_memtable(self, old: Memtable) -> None:
        """Flush-failure recovery: swap the retired memtable back in
        under the exclusive barrier (writers quiesced) after absorbing
        the replacement's writes, so every acked write is still served
        from memory and the next flush retries the whole set."""
        with self._barrier.exclusive():
            current = self.memtable
            if not current.is_empty:
                old.absorb(current)
            self.memtable = old

    @staticmethod
    def _append_pipelined(old: Memtable, writer: SSTableWriter) -> None:
        """Drain → compress → io as three overlapped stages: a drain
        thread runs the memtable's shard sort generator into a bounded
        queue (backpressure: two runs in flight), the flush thread packs
        each run through the writer's native compressor, and the
        writer's own I/O thread lands bytes on disk. The drain stage
        reports into the `flush` pipeline ledger: busy = shard
        drain+sort seconds, stall = seconds parked on the full queue
        (downstream backpressure)."""
        import queue

        from ..utils import pipeline_ledger
        drain_led = pipeline_ledger.ledger("flush").stage("drain")
        q: queue.Queue = queue.Queue(maxsize=2)
        err: list[BaseException] = []

        def _drain():
            try:
                t_prev = time.perf_counter()
                for run in old.flush_shards():
                    t1 = time.perf_counter()
                    drain_led.add_busy(t1 - t_prev)
                    drain_led.add_items(
                        1, getattr(getattr(run, "payload", None),
                                   "nbytes", 0))
                    drain_led.note_queue(q.qsize())
                    q.put(run)
                    t_prev = time.perf_counter()
                    drain_led.add_stall(t_prev - t1)
            except BaseException as e:   # surfaced on the flush thread
                err.append(e)
            finally:
                q.put(None)

        t = threading.Thread(target=_drain, daemon=True,
                             name="memtable-drain")
        t.start()
        done = False
        try:
            while True:
                run = q.get()
                if run is None:
                    done = True
                    break
                writer.append(run)
        finally:
            # if append raised, the producer may be parked on a full
            # queue: drain to its terminal None so join cannot hang
            while not done:
                done = q.get() is None
            t.join()
        if err:
            raise err[0]

    def _backup_sstable(self, desc) -> None:
        """Hardlink a freshly-flushed sstable's components into
        backups/ (incremental_backups: every flushed sstable is
        retained there until the operator clears it — zero copy cost,
        links share the immutable data blocks)."""
        bdir = os.path.join(self.directory, "backups")
        os.makedirs(bdir, exist_ok=True)
        prefix = f"{desc.version}-{desc.generation}-"
        for fn in os.listdir(self.directory):
            if fn.startswith(prefix):
                dst = os.path.join(bdir, fn)
                if not os.path.exists(dst):
                    try:
                        os.link(os.path.join(self.directory, fn), dst)
                    except OSError:
                        import shutil
                        shutil.copy2(os.path.join(self.directory, fn),
                                     dst)

    # -------------------------------------------------------------- read --

    def _collate_sources(self, pk: bytes) -> tuple[list, int]:
        """Gather the partition's per-source views: memtable first, then
        sstables. With the fast lane on (CTPU_READ_FASTPATH), sstables
        are consulted in DESCENDING max_ts order and consultation STOPS
        as soon as the accumulated state is provably newer than every
        remaining sstable: once a partition-scope deletion with
        timestamp D has been collected, a remaining sstable whose
        max_ts < D cannot contribute — every cell it could hold
        (including its tombstones; the skip is tombstone-aware because
        deletion shadowing uses ts <= D, see CellBatch.reconcile step 3)
        is shadowed by D, so the merged result is bit-identical to the
        full collation (the reference's mostRecentPartitionTombstone
        break in SinglePartitionReadCommand.queryMemtableAndDisk).
        Timestamps ALONE never justify a skip: an older sstable may hold
        rows the newer state does not shadow (docs/read-path.md).

        Returns (sources, sstables_consulted) where consulted counts
        sstables that passed their bloom filter and did index/data work.
        """
        fast = read_fastpath_enabled()
        sources = []
        top_pd_ts = None
        mem = self.memtable
        m = mem.read_partition(pk)
        if m is not None:
            sources.append(m)
            top_pd_ts = _partition_deletion_ts(m)
        ssts = self.tracker.view_by_max_ts() if fast \
            else self.tracker.view()
        consulted = 0
        for sst in ssts:
            if fast and top_pd_ts is not None and sst.max_ts < top_pd_ts:
                # ts-descending order: every remaining sstable is at
                # least as old — stop, don't just skip this one
                break
            if not sst.might_contain(pk):
                continue
            consulted += 1
            try:
                part = sst.read_partition(pk)
            except (CorruptSSTableError, OSError) as e:
                # graceful degradation: under best_effort the corrupt
                # source is quarantined and the merge continues over
                # the remaining sstables (obsolete data possible at
                # CL.ONE — reference best_effort semantics)
                self._degrade_on_corruption(sst, e)
                continue
            if part is not None:
                sources.append(part)
                t = _partition_deletion_ts(part)
                if t is not None and (top_pd_ts is None or t > top_pd_ts):
                    top_pd_ts = t
        return sources, consulted

    def read_partition(self, pk: bytes, now: int | None = None,
                       limits=None) -> CellBatch:
        """Merged view of one partition across memtable + sstables
        (SinglePartitionReadCommand.queryMemtableAndDisk role).
        `limits` (cellbatch.DataLimits) truncates the RETURNED view at
        the limit-th live row — the full merge still happens (and still
        feeds the row cache); truncation spares downstream assembly and,
        replica-side, the wire."""
        self.failures.check_can_read()
        self.metrics["reads"] += 1
        _t0 = time.perf_counter()
        from ..service.tracing import active, trace
        now = now if now is not None else timeutil.now_seconds()
        read_gen = None
        if self.row_cache is not None:
            cached = self.row_cache.get(pk)
            if cached is not None:
                if active() is not None:
                    trace("Row cache hit")
                if limits is not None:
                    cached, _ = truncate_live_rows(cached, limits)
                self.read_hist.update_us(
                    (time.perf_counter() - _t0) * 1e6)
                return cached
            # captured BEFORE the source snapshot (see RowCache.put)
            read_gen = self.row_cache.generation
        sources, consulted = self._collate_sources(pk)
        self.sstables_per_read.update_us(consulted)
        if active() is not None:   # tracing off: zero-cost path
            trace(f"Merging {len(sources)} source(s) for partition read")
        if not sources:
            from .cellbatch import lanes_for_table
            merged = CellBatch.empty(lanes_for_table(self.table))
        else:
            merged = merge_sorted(sources, now=now)
        if self.row_cache is not None:
            self.row_cache.put(pk, merged, read_gen)
        if limits is not None:
            merged, _ = truncate_live_rows(merged, limits)
        self.read_hist.update_us((time.perf_counter() - _t0) * 1e6)
        return merged

    # batched reads at or above this many outstanding keys route
    # through the mesh fan-out when `compaction_mesh_devices` is on
    MESH_READ_MIN_KEYS = 16

    def _batched_merge(self, pending: list[bytes], now: int,
                       shard_merge: bool = False,
                       lane_map: dict | None = None) -> tuple[dict, dict]:
        """One batched collation pass over a key subset: memtable
        sources, then the timestamp-skip sstable walk with one
        vectorized probe per sstable, then the merge. Returns
        ({pk: merged CellBatch}, {pk: sstables consulted}). This is the
        unit the mesh read route fans out per token shard — keys are
        independent, so any sharding of `pending` yields results
        identical to one pass over the whole list.

        shard_merge=True (the mesh route) merges the whole subset's
        sources in ONE kernel call and slices the result back per
        partition (_shard_merge_slices) instead of running len(pending)
        tiny per-key merges: identical results, but the work becomes
        chunky GIL-releasing numpy/native ops that actually overlap
        across mesh lanes."""
        mem = self.memtable
        sources = {pk: [] for pk in pending}
        top_pd: dict[bytes, int] = {}
        consulted = {pk: 0 for pk in pending}
        for pk in pending:
            m = mem.read_partition(pk)
            if m is not None:
                sources[pk].append(m)
                t = _partition_deletion_ts(m)
                if t is not None:
                    top_pd[pk] = t
        active_pks = list(pending)
        for sst in self.tracker.view_by_max_ts():
            # keys whose accumulated partition deletion already
            # covers this (and every remaining) sstable drop out
            active_pks = [pk for pk in active_pks
                          if top_pd.get(pk) is None
                          or sst.max_ts >= top_pd[pk]]
            if not active_pks:
                break
            try:
                parts, passed = sst.read_partitions_batch(active_pks)
            except (CorruptSSTableError, OSError) as e:
                # same degradation contract as the single-key path
                self._degrade_on_corruption(sst, e)
                continue
            for pk in passed:
                consulted[pk] += 1
            for pk, part in parts.items():
                sources[pk].append(part)
                t = _partition_deletion_ts(part)
                if t is not None and (pk not in top_pd
                                      or t > top_pd[pk]):
                    top_pd[pk] = t
        from .cellbatch import lanes_for_table
        if shard_merge:
            return self._shard_merge_slices(pending, sources, now,
                                            lane_map), consulted
        merged_map: dict[bytes, CellBatch] = {}
        for pk in pending:
            if not sources[pk]:
                merged_map[pk] = CellBatch.empty(
                    lanes_for_table(self.table))
            else:
                merged_map[pk] = merge_sorted(sources[pk], now=now)
        return merged_map, consulted

    def _shard_merge_slices(self, pending: list[bytes], sources: dict,
                            now: int,
                            lane_map: dict | None = None) -> dict:
        """One chunky merge for a whole token-range shard instead of
        len(pending) tiny per-key merges. All keys' source parts flatten
        into one merge_sorted call (per-key part order preserved, so
        every identity's reconciliation inputs are exactly the per-key
        merge's — identities never span partitions, so the winners are
        identical), and the sorted result slices back per partition by
        its lane boundaries. The per-key formulation is >80% interpreter
        overhead at batch scale (measured: 2048 keys x 3 sstables spend
        7.2s of 8.6s in per-key merge_sorted); this one is vectorized
        work that releases the GIL — which is what lets the mesh lanes
        actually overlap instead of serializing on the interpreter."""
        from .cellbatch import lanes_for_table, pk_lanes

        lanes = lanes_for_table(self.table)
        out = {pk: CellBatch.empty(lanes) for pk in pending}
        parts = [p for pk in pending for p in sources[pk]]
        if not parts:
            return out
        merged = merge_sorted(parts, now=now)
        n = len(merged)
        if n == 0:
            return out
        part_new = np.ones(n, dtype=bool)
        part_new[1:] = (merged.lanes[1:, :4]
                        != merged.lanes[:-1, :4]).any(axis=1)
        starts = np.flatnonzero(part_new)
        ends = np.append(starts[1:], n)
        slot = {tuple(int(x) for x in merged.lanes[s, :4]): i
                for i, s in enumerate(starts)}
        for pk in pending:
            # one murmur3/token hash per key per request: the mesh route
            # computed these when it planned the shards
            lt = lane_map[pk] if lane_map is not None \
                else tuple(pk_lanes(pk))
            i = slot.get(lt)
            if i is None:
                continue   # absent, or fully purged in the merge
            key = b"".join(int(x).to_bytes(4, "big") for x in lt)
            out[pk] = self._copy_slice(merged, int(starts[i]),
                                       int(ends[i]), {key: pk})
        return out

    @staticmethod
    def _copy_slice(b: CellBatch, lo: int, hi: int,
                    pk_map: dict) -> CellBatch:
        """Owned copy of rows [lo, hi) — unlike CellBatch.slice_range's
        zero-copy views, results handed to callers (and pinned by the
        row cache) must not keep the whole shard's arrays alive. The
        caller supplies the slice's OWN pk_map (one partition → one
        entry): sharing the shard's full map would pin every key's pk
        bytes in the row cache and ship the whole map per partition in
        coordinator serialization."""
        base = int(b.off[lo])
        out = CellBatch(b.lanes[lo:hi].copy(), b.ts[lo:hi].copy(),
                        b.ldt[lo:hi].copy(), b.ttl[lo:hi].copy(),
                        b.flags[lo:hi].copy(), b.off[lo:hi + 1] - base,
                        b.val_start[lo:hi] - base,
                        b.payload[base:int(b.off[hi])].copy(),
                        pk_map, sorted=True)
        out.ck_comp = b.ck_comp
        out.ck_fits_prefix = b.ck_fits_prefix
        return out

    def _mesh_boundaries(self, n_shards: int):
        """Count-weighted token boundaries over the live sstable set
        (parallel/mesh.boundaries_from_indexes), cached per (live
        generations, n_shards): the plan walks every live partition
        directory, but only changes when the sstable set does —
        flush/compaction/quarantine all change the generation tuple,
        so the key self-invalidates."""
        view = self.tracker.view()
        if not view:
            return None
        key = (tuple(r.desc.generation for r in view), n_shards)
        cached = self._mesh_bounds_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        from ..parallel.boundaries import boundaries_from_indexes
        bounds = boundaries_from_indexes(view, n_shards)
        self._mesh_bounds_cache = (key, bounds)
        return bounds

    def _mesh_read_shards(self, pending: list[bytes],
                          n_shards: int) -> tuple[list, dict] | None:
        """Split a large key batch into token-range shards by the
        count-weighted quantile boundaries planned from the live
        sstables' partition indexes (the same planner mesh compaction
        uses). Returns (non-empty shard key lists, pk -> partition-lane
        tuples — hashed ONCE here and reused by the shard merges), or
        None when the table has no index samples or everything lands in
        one shard."""
        if n_shards < 2:
            return None
        bounds = self._mesh_boundaries(n_shards)
        if bounds is None or not len(bounds):
            return None
        from .cellbatch import pk_lanes
        lane_map = {pk: pk_lanes(pk) for pk in pending}
        lanes = np.array([lane_map[pk] for pk in pending],
                         dtype=np.uint64)
        tok = (lanes[:, 0] << np.uint64(32)) | lanes[:, 1]
        shard_of = np.searchsorted(np.asarray(bounds, dtype=np.uint64),
                                   tok, side="left")
        shards = [[] for _ in range(n_shards)]
        for pk, s in zip(pending, shard_of):
            shards[int(s)].append(pk)
        shards = [s for s in shards if s]
        return (shards, lane_map) if len(shards) >= 2 else None

    def read_partitions(self, pks: list[bytes], now: int | None = None,
                        limits=None) -> list[tuple[bytes, CellBatch]]:
        """Batched multi-partition read (the `IN (...)` / multi-key
        internal-read fast lane). Per sstable, ALL still-outstanding keys
        resolve their bloom + key-cache + partition-directory candidates
        in one vectorized probe and the hit segments decode once for
        every partition they cover (SSTableReader.read_partitions_batch)
        instead of N independent read_partition walks. Timestamp-skip
        collation applies per key, exactly as in read_partition. Returns
        [(pk, merged batch)] in input order; duplicate keys share one
        merge. Falls back to per-key reads when the fastpath is off."""
        self.failures.check_can_read()
        if not read_fastpath_enabled():
            return [(pk, self.read_partition(pk, now=now, limits=limits))
                    for pk in pks]
        _t0 = time.perf_counter()
        from ..service.tracing import active, trace
        now = now if now is not None else timeutil.now_seconds()
        self.metrics["reads"] += len(pks)
        merged: dict[bytes, CellBatch] = {}
        read_gen = None
        pending: list[bytes] = []
        for pk in dict.fromkeys(pks):       # unique, input-ordered
            if self.row_cache is not None:
                cached = self.row_cache.get(pk)
                if cached is not None:
                    merged[pk] = cached
                    continue
            pending.append(pk)
        if self.row_cache is not None and pending:
            read_gen = self.row_cache.generation
        if pending:
            from ..parallel import fanout as fanout_mod
            n_mesh = self.mesh_devices_fn()
            fan = fanout_mod.get_fanout() if n_mesh > 0 else None
            shard_lists = lane_map = None
            if fan is not None and len(pending) >= self.MESH_READ_MIN_KEYS:
                sharded = self._mesh_read_shards(pending, n_mesh)
                if sharded is not None:
                    shard_lists, lane_map = sharded
            if shard_lists is not None:
                # mesh route: keys sharded by the count-weighted token
                # boundaries from the sstable partition indexes, one
                # collation pass per shard across the mesh lanes. Keys
                # are independent, so sharded results == serial results.
                from ..service.metrics import GLOBAL as _MESH_M
                _MESH_M.incr("mesh.batch_reads")
                _MESH_M.incr("mesh.read_keys", len(pending))
                # shard dispatch/completion under the active trace:
                # lanes run on fanout worker threads (no contextvar),
                # so the coordinator's TraceState is captured here and
                # appended to directly — PR 8's lanes were invisible in
                # system_traces.events without this
                _tr = active()

                def _run_shard(s):
                    if _tr is not None:
                        _tr.add(f"Mesh read shard {s} dispatched "
                                f"({len(shard_lists[s])} key(s))")
                    out = self._batched_merge(shard_lists[s], now,
                                              shard_merge=True,
                                              lane_map=lane_map)
                    if _tr is not None:
                        _tr.add(f"Mesh read shard {s} complete")
                    return out

                outs = fan.map_shards(_run_shard, len(shard_lists))
                merged_map: dict[bytes, CellBatch] = {}
                consulted: dict[bytes, int] = {}
                for m_map, cons in outs:
                    merged_map.update(m_map)
                    consulted.update(cons)
            else:
                merged_map, consulted = self._batched_merge(pending, now)
            if active() is not None:
                trace(f"Batched read: {len(pending)} partition(s), "
                      f"{len(self.tracker.view())} live sstable(s)"
                      + (f", {len(shard_lists)} mesh shard(s)"
                         if shard_lists is not None else ""))
            for pk in pending:
                self.sstables_per_read.update_us(consulted[pk])
                m = merged_map[pk]
                if self.row_cache is not None:
                    self.row_cache.put(pk, m, read_gen)
                merged[pk] = m
        self.multiread_hist.update_us((time.perf_counter() - _t0) * 1e6)
        if limits is None:
            return [(pk, merged[pk]) for pk in pks]
        return [(pk, truncate_live_rows(merged[pk], limits)[0])
                for pk in pks]

    def scan_all(self, now: int | None = None) -> CellBatch:
        """Full-table merged view (range-read building block). With the
        mesh lanes on (`compaction_mesh_devices`), the scan shards by
        the count-weighted token boundaries and each shard's
        decode+merge runs on its own lane; the shards concatenate in
        token order into exactly the serial merge (token-range shard
        order IS identity-lane order)."""
        self.failures.check_can_read()
        now = now if now is not None else timeutil.now_seconds()
        from ..parallel import fanout as fanout_mod
        n_mesh = self.mesh_devices_fn()
        fan = fanout_mod.get_fanout() if n_mesh > 0 else None
        if fan is not None and self.tracker.view():
            from ..parallel.boundaries import boundaries_to_ranges
            bounds = self._mesh_boundaries(n_mesh)
            if bounds is not None and len(bounds):
                from ..service.metrics import GLOBAL as _MESH_M
                _MESH_M.incr("mesh.range_scans")
                ranges = boundaries_to_ranges(bounds, len(bounds) + 1)
                parts = fan.map_shards(
                    lambda s: self.scan_window(ranges[s][0], ranges[s][1],
                                               now=now),
                    len(ranges))
                parts = [p for p in parts if len(p)]
                if not parts:
                    from .cellbatch import lanes_for_table
                    return CellBatch.empty(lanes_for_table(self.table))
                out = parts[0] if len(parts) == 1 \
                    else CellBatch.concat(parts)
                out.sorted = True
                return out
        sources = [self.memtable.scan()]
        for sst in self.tracker.view():
            try:
                segs = list(sst.scanner())
            except (CorruptSSTableError, OSError) as e:
                # full scans degrade like scan_window/point reads
                # (best_effort quarantines the rotten source and the
                # scan continues) — and identically to the mesh route,
                # which reaches the same handling via scan_window, so
                # the error surface does not depend on the mesh knob
                self._degrade_on_corruption(sst, e)
                continue
            if segs:
                cat = CellBatch.concat(segs)
                cat.sorted = True
                sources.append(cat)
        return merge_sorted([s for s in sources if len(s)] or sources[:1],
                            now=now)

    def scan_window(self, lo: int, hi: int,
                    now: int | None = None) -> CellBatch:
        """Merged view of partitions with token in (lo, hi] — the bounded
        range-read primitive behind paging (service/pager/QueryPagers
        role: read a window, not the table)."""
        self.failures.check_can_read()
        now = now if now is not None else timeutil.now_seconds()
        sources = [self.memtable.scan_window(lo, hi)]
        for sst in self.tracker.view():
            try:
                w = sst.scan_tokens(lo, hi)
            except (CorruptSSTableError, OSError) as e:
                # range reads degrade like point reads (best_effort
                # quarantines the rotten source and the scan continues)
                self._degrade_on_corruption(sst, e)
                continue
            if w is not None and len(w):
                sources.append(w)
        sources = [s for s in sources if len(s)]
        if not sources:
            from .cellbatch import lanes_for_table
            return CellBatch.empty(lanes_for_table(self.table))
        return merge_sorted(sources, now=now)

    def scan_filtered(self, pred, now: int | None = None,
                      use_device=None) -> tuple[list, dict]:
        """Analytical scan fast lane. Phase A discovers the partitions
        that MAY hold a row matching `pred` without assembling any
        rows: per sstable, zone maps (index/sstable_index.py ZMP1)
        prune whole segments — and whole sstables — before decode, and
        the surviving segments' value lanes run through the
        ops/device_scan.py predicate kernels (host numpy reference per
        segment on fallback, results identical). Phase B reads JUST the
        candidate partitions through read_partitions, so callers get
        exactly the merged, reconciled view a naive full scan would
        have produced for those partitions — Phase A is a provable
        superset (a winning live cell exists in some source and its
        segment/zone bounds contain its key), and the executor
        re-verifies every candidate row with the exact predicate.

        With the mesh lanes on, Phase A fans token-range shards across
        the fanout exactly like scan_all; candidates drain in token
        order. `use_device`: None = consult the engine's hot-reloadable
        `scan_device_filter` knob PER SEGMENT; bool = pin; callable =
        consulted per segment (the device_compress gate pattern — a
        mid-scan flip moves work at the next segment boundary).

        Returns ([(pk, merged CellBatch)] in token order, info dict
        with the prune accounting)."""
        self.failures.check_can_read()
        now = now if now is not None else timeutil.now_seconds()
        from ..index import sstable_index as ssi_mod
        from ..ops import device_scan as ds
        from ..service.metrics import GLOBAL as _M
        from ..utils import pipeline_ledger
        from .cellbatch import batch_tokens, pk_lanes
        led = pipeline_ledger.ledger("scan")
        st_prune = led.stage("prune")
        st_filter = led.stage("filter")
        st_gather = led.stage("gather")

        if use_device is None:
            gate = self.scan_device_filter_fn
        elif callable(use_device):
            gate = use_device
        else:
            gate = lambda _v=bool(use_device): _v  # noqa: E731

        _KEYS = ("segments_total", "segments_skipped",
                 "sstables_skipped", "device_segments", "host_segments")
        info = dict.fromkeys(_KEYS, 0)

        def _scan_sources(view, lo, hi):
            """Candidate pks among `view` for tokens in (lo, hi]."""
            pks = set()
            loc = dict.fromkeys(_KEYS, 0)
            for sst in view:
                try:
                    span = sst.segment_range_for_tokens(lo, hi)
                    if span is None:
                        continue
                    s0, s1 = span
                    with st_prune.busy():
                        zm = ssi_mod.zonemap_for(sst, self.table)
                        keep = zm.keep_mask(pred)[s0:s1] \
                            if zm is not None \
                            else np.ones(s1 - s0, dtype=bool)
                    loc["segments_total"] += s1 - s0
                    n_keep = int(keep.sum())
                    loc["segments_skipped"] += (s1 - s0) - n_keep
                    if n_keep == 0:
                        loc["sstables_skipped"] += 1
                        continue
                    for s in range(s0, s1):
                        if not keep[s - s0]:
                            continue
                        batch = sst._read_segment(s)
                        with st_filter.busy():
                            sel, keys = ds.batch_predicate_cells(
                                batch, pred, reconciled=False)
                            if not len(sel):
                                continue
                            mask, on_dev = ds.segment_mask(
                                keys, pred, bool(gate()))
                        loc["device_segments" if on_dev
                            else "host_segments"] += 1
                        st_filter.add_items(len(sel))
                        hit = sel[mask]
                        if not len(hit):
                            continue
                        toks = batch_tokens(batch)[hit]
                        for i in hit[(toks > lo) & (toks <= hi)]:
                            pks.add(batch.partition_key(int(i)))
                except (CorruptSSTableError, OSError) as e:
                    # sharded scans degrade per SOURCE like scan_window
                    self._degrade_on_corruption(sst, e)
                    continue
            return pks, loc

        pks: set = set()
        # memtable: always scanned on the coordinator (small, always
        # fresh, no zone maps to consult)
        mem = self.memtable.scan()
        if len(mem):
            with st_filter.busy():
                sel, keys = ds.batch_predicate_cells(mem, pred,
                                                     reconciled=False)
                if len(sel):
                    mask, _ = ds.segment_mask(keys, pred, bool(gate()))
                    for i in sel[mask]:
                        pks.add(mem.partition_key(int(i)))
        view = self.tracker.view()
        from ..parallel import fanout as fanout_mod
        n_mesh = self.mesh_devices_fn()
        fan = fanout_mod.get_fanout() if n_mesh > 0 else None
        ranges = None
        if fan is not None and view:
            from ..parallel.boundaries import boundaries_to_ranges
            bounds = self._mesh_boundaries(n_mesh)
            if bounds is not None and len(bounds):
                ranges = boundaries_to_ranges(bounds, len(bounds) + 1)
        if ranges is not None:
            _M.incr("scan.mesh_scans")
            outs = fan.map_shards(
                lambda s: _scan_sources(view, ranges[s][0],
                                        ranges[s][1]),
                len(ranges))
            for ps, loc in outs:
                pks |= ps
                for k in _KEYS:
                    info[k] += loc[k]
        elif view:
            ps, loc = _scan_sources(view, -(1 << 63), (1 << 63) - 1)
            pks |= ps
            for k in _KEYS:
                info[k] += loc[k]
        for k in _KEYS:
            if info[k]:
                _M.incr(f"scan.{k}", info[k])
        _M.incr("scan.candidates", len(pks))
        # lane order IS token order (the bias-xor is order-preserving)
        ordered = sorted(pks, key=pk_lanes)
        info["candidates"] = len(ordered)
        with st_gather.busy():
            out = self.read_partitions(ordered, now=now) if ordered \
                else []
        st_gather.add_items(len(out))
        return out, info

    def scan_filtered_aggregate(self, pred, now: int | None = None,
                                use_device=None) -> tuple:
        """Exact (count, min, max, int_sum, info) of the predicate
        column over the reconciled candidate partitions — the
        aggregation leg that never materializes a row dict host-side.
        Only valid for EXACT predicate kinds (pred.exact): there the
        key-space mask equals the executor's `_match` row for row on
        reconciled batches, so the device fold IS the aggregate."""
        from ..ops import device_scan as ds
        from .cellbatch import CellBatch
        batches, info = self.scan_filtered(pred, now=now,
                                           use_device=use_device)
        if use_device is None:
            gate = self.scan_device_filter_fn
        elif callable(use_device):
            gate = use_device
        else:
            gate = lambda _v=bool(use_device): _v  # noqa: E731
        parts = [b for _pk, b in batches if len(b)]
        if not parts:
            info["fold_on_device"] = False
            return 0, None, None, 0, info
        big = CellBatch.concat(parts) if len(parts) > 1 else parts[0]
        cnt, kmn, kmx, sm, on_dev = ds.fold_batch(big, pred,
                                                  bool(gate()))
        info["fold_on_device"] = on_dev
        if cnt == 0:
            return 0, None, None, 0, info
        return (cnt, ds.value_of_key(pred.kind, kmn),
                ds.value_of_key(pred.kind, kmx), sm, info)

    def next_partition_tokens(self, after: int, k: int) -> list[int]:
        """The first k distinct partition tokens > after, across the
        memtable and every sstable's partition directory — how the pager
        sizes its next window without scanning data."""
        cands: set[int] = set()
        side = "left" if after == -(1 << 63) else "right"
        from .cellbatch import batch_tokens
        mem = self.memtable.scan()
        if len(mem):
            toks = batch_tokens(mem)
            i = int(np.searchsorted(toks, after, side=side))
            uniq = np.unique(toks[i:])
            cands.update(int(t) for t in uniq[:k])
        for sst in self.tracker.view():
            toks = sst.partition_tokens
            i = int(np.searchsorted(toks, after, side=side))
            cands.update(int(t) for t in toks[i:i + k])
        return sorted(cands)[:k]

    def iter_scan(self, now: int | None = None, after: int = -(1 << 63),
                  window_parts: int = 64, limits=None):
        """Yield merged CellBatches window by window, each window covering
        up to window_parts partitions — full scans in bounded memory.
        `limits` truncates each window at its live-row bound (the local
        leg of the DataLimits range pushdown — spares row assembly)."""
        now = now if now is not None else timeutil.now_seconds()
        pos = after
        while True:
            toks = self.next_partition_tokens(pos, window_parts)
            if not toks:
                return
            hi = toks[-1]
            batch = self.scan_window(pos, hi, now=now)
            if limits is not None:
                # local leg of the range DataLimits pushdown: spare the
                # row assembly beyond the limit (distributed stores
                # truncate replica-side and track `more` themselves)
                batch, _ = truncate_live_rows(batch, limits)
            if len(batch):
                yield batch
            pos = hi

    # --------------------------------------------------------------- misc --

    def live_sstables(self) -> list[SSTableReader]:
        return self.tracker.view()

    def truncate(self) -> None:
        if self.row_cache is not None:
            self.row_cache.clear()
        with self._barrier.exclusive():
            self.memtable = Memtable(self.table,
                                     shards=self.memtable_shards)
            old = self.tracker.view()
            self.tracker.replace(old, [])
            from .chunk_cache import GLOBAL as chunk_cache
            from .key_cache import GLOBAL as key_cache
            for sst in old:
                sst.close()
                chunk_cache.invalidate_generation(sst.desc.directory,
                                                  sst.desc.generation)
                key_cache.invalidate_generation(sst.desc.directory,
                                                sst.desc.generation)
                # the whole generation family: standard components AND
                # attached index components (Index_<col>.db)
                prefix = f"{sst.desc.version}-{sst.desc.generation}-"
                for fn in os.listdir(self.directory):
                    if fn.startswith(prefix):
                        os.remove(os.path.join(self.directory, fn))
        if self.row_cache is not None:
            # again AFTER the switch: a read that raced the truncate
            # may have re-cached pre-truncate content
            self.row_cache.clear()
