"""AutoSavingCache round-trips (storage/saved_caches.py) and the bloom
filter's false-positive-rate bound (utils/bloom.py) — satellite coverage
for the read-path fast lane's cache hierarchy."""
import os

import numpy as np

from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.key_cache import GLOBAL as key_cache
from cassandra_tpu.storage.saved_caches import AutoSavingCache
from cassandra_tpu.utils.bloom import BloomFilter


def _engine_with_data(tmp_path, caching=False):
    eng = StorageEngine(str(tmp_path / "d"), Schema(),
                        commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    opt = (" WITH caching = {'keys': 'ALL', 'rows_per_partition': 'ALL'}"
           if caching else "")
    s.execute("CREATE TABLE kv (k int, c int, v text, "
              "PRIMARY KEY (k, c))" + opt)
    for k in range(8):
        for c in range(3):
            s.execute(f"INSERT INTO kv (k, c, v) VALUES ({k}, {c}, "
                      f"'v{k}{c}')")
    cfs = eng.store("ks", "kv")
    cfs.flush()
    return eng, s, cfs


def test_key_cache_save_load_roundtrip(tmp_path):
    """Keys (never values) persist: save after reads, clear, warm —
    the key cache refills through SSTableReader.warm_key and the next
    point read hits it."""
    eng, s, cfs = _engine_with_data(tmp_path)
    key_cache.clear()
    for k in range(8):
        s.execute(f"SELECT v FROM kv WHERE k = {k}")   # populate
    assert len(key_cache.keys()) == 8
    saver = AutoSavingCache(eng)
    counts = saver.save()
    assert counts["key"] == 8
    assert os.path.exists(os.path.join(saver.directory,
                                       AutoSavingCache.KEY_FILE))

    key_cache.clear()
    assert len(key_cache.keys()) == 0
    warmed = saver.warm()
    assert warmed["key"] == 8
    assert len(key_cache.keys()) == 8
    h0 = key_cache.hits
    s.execute("SELECT v FROM kv WHERE k = 3")
    assert key_cache.hits > h0
    saver.close()
    eng.close()


def test_key_cache_warm_skips_compacted_generations(tmp_path):
    """A save file referencing generations compacted away since must
    warm nothing for them (and must not crash)."""
    eng, s, cfs = _engine_with_data(tmp_path)
    key_cache.clear()
    for k in range(8):
        s.execute(f"SELECT v FROM kv WHERE k = {k}")
    saver = AutoSavingCache(eng)
    saver.save()
    # a second sstable + major compaction replaces every generation
    s.execute("INSERT INTO kv (k, c, v) VALUES (0, 9, 'x')")
    cfs.flush()
    eng.compactions.major_compaction(cfs)
    key_cache.clear()
    warmed = saver.warm()
    assert warmed["key"] == 0
    saver.close()
    eng.close()


def test_row_cache_keys_roundtrip(tmp_path):
    """Row-cache KEYS persist; warm re-reads through the normal read
    path, so the cache refills with current on-disk truth."""
    eng, s, cfs = _engine_with_data(tmp_path, caching=True)
    assert cfs.row_cache is not None
    for k in range(8):
        s.execute(f"SELECT v FROM kv WHERE k = {k}")
    assert len(cfs.row_cache) == 8
    saver = AutoSavingCache(eng)
    counts = saver.save()
    assert counts["row"] == 8
    cfs.row_cache.clear()
    assert len(cfs.row_cache) == 0
    warmed = saver.warm()
    assert warmed["row"] == 8
    assert len(cfs.row_cache) == 8
    h0 = cfs.row_cache.hits
    s.execute("SELECT v FROM kv WHERE k = 5")
    assert cfs.row_cache.hits > h0
    saver.close()
    eng.close()


def test_bloom_false_positive_rate_bound():
    """The filter built for fp_rate=0.01 stays within 2x of its target
    on absent keys and never reports a false negative."""
    n = 10_000
    bf = BloomFilter.create(n, fp_rate=0.01)
    present = [b"key-%d" % i for i in range(n)]
    bf.add_batch(present)
    assert bool(bf.might_contain_batch(present).all())   # no false negs
    absent = [b"absent-%d" % i for i in range(2 * n)]
    fp = int(bf.might_contain_batch(absent).sum())
    assert fp / len(absent) < 0.02, fp


def test_bloom_fp_rate_tracks_target_across_densities():
    rng = np.random.default_rng(5)
    for target in (0.1, 0.01):
        n = 5_000
        bf = BloomFilter.create(n, fp_rate=target)
        keys = [bytes(k) for k in rng.integers(
            0, 256, (n, 12)).astype(np.uint8)]
        bf.add_batch(keys)
        absent = [b"x" + bytes(k) for k in rng.integers(
            0, 256, (10_000, 12)).astype(np.uint8)]
        fp = int(bf.might_contain_batch(absent).sum()) / len(absent)
        assert fp < 2.5 * target, (target, fp)
