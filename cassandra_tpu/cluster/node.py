"""Node: one database instance (engine + messaging + gossip + coordinator)
and LocalCluster: N nodes in one process with interceptable messaging —
the jvm-dtest harness (reference test/distributed/impl/AbstractCluster.java:
one Instance per classloader, in-memory message routing, MessageFilters).
"""
from __future__ import annotations

import os
import threading
import time

from ..cql.execution import Executor
from ..cql.processor import Session
from ..schema import Schema
from ..storage import cellbatch as cbmod
from ..storage.engine import StorageEngine
from ..storage.mutation import Mutation
from .coordinator import StorageProxy, cb_serialize
from .gossip import Gossiper
from .hints import HintsService
from .messaging import LocalTransport, MessagingService, Verb
from .replication import ConsistencyLevel
from .ring import Endpoint, Ring, even_tokens


class Node:
    def __init__(self, endpoint: Endpoint, data_dir: str, schema: Schema,
                 ring: Ring, transport: LocalTransport,
                 seeds: list[Endpoint], gossip_interval: float = 0.1,
                 engine_opts: dict | None = None):
        self.endpoint = endpoint
        self.schema = schema
        self.ring = ring
        self.engine = StorageEngine(data_dir, schema,
                                    commitlog_sync="batch",
                                    **(engine_opts or {}))
        self.messaging = MessagingService(endpoint, transport)
        self.hints = HintsService(os.path.join(data_dir, "hints"))
        self.gossiper = Gossiper(self.messaging, seeds,
                                 interval=gossip_interval)
        self.gossiper.on_alive = self._on_peer_alive
        self.gossiper.on_dead = self._on_peer_dead
        # runtime knobs for the liveness/hints machinery (ctpulint
        # knob-wiring): phi_convict_threshold drives the failure
        # detector live, max_hint_window (seconds in config) feeds the
        # ms-denominated window below, hinted_handoff_enabled follows
        # the same gate nodetool disablehandoff flips
        self._settings_subs: list = []
        _settings = getattr(self.engine, "settings", None)
        if _settings is not None:
            det = self.gossiper.detector
            det.threshold = float(_settings.get("phi_convict_threshold"))
            self.max_hint_window_ms = \
                float(_settings.get("max_hint_window")) * 1000.0
            self.hints.enabled = bool(
                _settings.get("hinted_handoff_enabled"))
            self.messaging.set_dispatch_workers(
                int(_settings.get("internode_dispatch_threads")))
            for name, cb_ in (
                    ("phi_convict_threshold",
                     lambda v: setattr(det, "threshold", float(v))),
                    ("max_hint_window",
                     lambda v: setattr(self, "max_hint_window_ms",
                                       float(v) * 1000.0)),
                    ("hinted_handoff_enabled",
                     lambda v: setattr(self.hints, "enabled", bool(v))),
                    # attribute re-read at fire time, so a restarted
                    # node's fresh MessagingService picks up later flips
                    ("internode_dispatch_threads",
                     lambda v: self.messaging.set_dispatch_workers(
                         int(v))),
                    # same re-read pattern: restart_node swaps in a
                    # fresh StreamService and later flips must land on
                    # the live one's token bucket
                    ("stream_throughput_outbound",
                     lambda v: self.streams.set_throughput(float(v))),
                    ("inter_dc_stream_throughput_outbound",
                     lambda v: self.streams.set_throughput(
                         float(v), inter_dc=True))):
                _settings.on_change(name, cb_)
                self._settings_subs.append((name, cb_))
        # disk/commit failure policy `stop`/`die`: the engine's failure
        # handler calls back so the node leaves the ring the way the
        # reference's StorageService.stopTransports does. on_stop only:
        # the die path chains into _stop, so registering on both would
        # run the transition (and push the DOWN event) twice
        self.engine.failures.on_stop(self._on_storage_failure)
        # server-push event bus (transport EVENT role): CQL servers and
        # tests subscribe; liveness/topology/schema transitions fan out
        self._event_listeners: list = []
        # last successful telemetry snapshot per peer (clusterstats'
        # staleness source); created HERE, not lazily — two racing
        # first pulls must not each mint a cache and drop the other's
        # last-known snapshots
        self._peer_telemetry: dict = {}
        self.proxy = StorageProxy(self)
        self._register_verbs()
        from .repair import RepairService
        self.repair = RepairService(self)
        from ..storage.virtual import build_node_virtuals
        self.virtual_tables = build_node_virtuals(self)
        from .paxos import PaxosService
        self.paxos = PaxosService(self)
        from .counters import CounterService
        self.counters = CounterService(self)
        from .streaming import StreamService
        self.streams = StreamService(self)
        self.default_cl = ConsistencyLevel.ONE
        # periodic hint dispatch (HintsDispatchExecutor role): hints must
        # flow even when the target was never convicted dead
        self._stop_hints = threading.Event()
        self._hint_thread = threading.Thread(
            target=self._hint_loop, daemon=True,
            name=f"hints-{endpoint.name}")
        self._hint_thread.start()

    def cas(self, keyspace, table, pk, ck, check_fn, mutation_fn):
        """Linearizable conditional write (StorageProxy.cas role)."""
        return self.paxos.cas(keyspace, table, pk, ck, check_fn,
                              mutation_fn, timeout=self.proxy.timeout)

    def cas_partition(self, keyspace, table, pk, check_and_build):
        """Partition-scoped CAS: conditional batches
        (StorageProxy.cas over BatchStatement conditions)."""
        return self.paxos.cas_partition(keyspace, table, pk,
                                        check_and_build,
                                        timeout=self.proxy.timeout)

    @property
    def batchlog(self):
        """Logged batches persist in the coordinator's batchlog before the
        replicated applies (BatchlogManager role)."""
        return self.engine.batchlog

    # reference default: 3h (cassandra.yaml max_hint_window)
    max_hint_window_ms = 3 * 3600 * 1000

    def should_hint(self, target) -> bool:
        """StorageProxy.shouldHint: no new hints for targets in a
        hint-disabled DC, or dead longer than the hint window (their
        backlog would only grow unboundedly — the node needs repair,
        not hints, when it returns)."""
        if not self.hints.enabled:
            # disablehandoff: without this gate a CL.ANY write to dead
            # replicas would ack on a hint store() silently dropped
            return False
        if target.dc in self.hints.disabled_dcs:
            return False
        st = self.gossiper.states.get(target)
        if st is not None and not st.alive and st.last_heartbeat != 0:
            # last_heartbeat == 0 means the peer was never heard from:
            # downtime is UNKNOWN, not "since the epoch" — the reference
            # (Gossiper.getEndpointDowntime) reports 0 there and hints.
            # Without this a replica marked down before its first
            # heartbeat silently lost every hint. (assassinate pushes
            # last_heartbeat far negative, so it still refuses here.)
            dead_s = self.gossiper.clock() - st.last_heartbeat
            if dead_s * 1000.0 > self.max_hint_window_ms:
                return False
        return True

    @property
    def guardrails(self):
        """The executor reads guardrails off its backend; a Node backend
        delegates to the engine's instance (one catalog per node)."""
        return self.engine.guardrails

    @property
    def audit_log(self):
        """Processor reads the audit/FQL streams off its backend —
        delegate so Node-backed sessions audit like engine-backed."""
        return self.engine.audit_log

    @property
    def fql_log(self):
        return self.engine.fql_log

    @property
    def settings(self):
        """Node-backed sessions read runtime settings (trace sampling,
        thresholds) off their backend like engine-backed ones."""
        return self.engine.settings

    @property
    def trace_store(self):
        """Coordinator-side trace sessions persist on this node's own
        engine store (system_traces role)."""
        return self.engine.trace_store

    # ------------------------------------------------------------- verbs --

    def _register_verbs(self):
        ms = self.messaging
        ms.register_handler(Verb.MUTATION_REQ, self._handle_mutation)
        ms.register_handler(Verb.READ_REQ, self._handle_read)
        ms.register_handler(Verb.RANGE_REQ, self._handle_range)
        ms.register_handler(Verb.HINT_REQ, self._handle_mutation)
        ms.register_handler(Verb.TRUNCATE_REQ, self._handle_truncate)
        ms.register_handler(Verb.INDEX_REQ, self._handle_index)
        ms.register_handler(Verb.METRICS_SNAPSHOT_REQ,
                            self._handle_metrics_snapshot)

    def _handle_mutation(self, msg):
        mutation = Mutation.deserialize(msg.payload)
        self.engine.apply(mutation)
        return Verb.MUTATION_RSP, b""

    def _handle_read(self, msg):
        keyspace, table_name, pk, *rest = msg.payload
        digest_only = bool(rest[0]) if rest else False
        limits = cbmod.DataLimits.from_wire(rest[1]) \
            if len(rest) > 1 else None
        batch = self.engine.store(keyspace, table_name).read_partition(pk)
        # DataLimits pushdown: truncate at the source so LIMIT 1 on a
        # huge partition ships bytes proportional to the limit, not the
        # partition (db/filter/DataLimits.java:44); `more` feeds the
        # coordinator's short-read protection
        batch, more = cbmod.truncate_live_rows(batch, limits)
        if digest_only:
            # digest read: 16 bytes back instead of the partition —
            # computed over the SAME limited view every replica produces
            return Verb.READ_RSP, cbmod.content_digest(batch)
        return Verb.READ_RSP, (cb_serialize(batch), more)

    def _handle_range(self, msg):
        keyspace, table_name, *window = msg.payload
        store = self.engine.store(keyspace, table_name)
        if window:
            lo, hi = window[0], window[1]
            batch = store.scan_window(int(lo), int(hi))
            if len(window) > 2 and window[2] is not None:
                # DataLimits pushdown for range reads: truncate the arc
                # response at the source (db/filter/DataLimits over
                # RangeCommands); `more` feeds per-arc short-read
                # protection at the coordinator
                limits = cbmod.DataLimits.from_wire(window[2])
                batch, more = cbmod.truncate_live_rows(batch, limits)
                return Verb.RANGE_RSP, (cb_serialize(batch), more)
        else:
            batch = store.scan_all()
        return Verb.RANGE_RSP, cb_serialize(batch)

    def _handle_index(self, msg):
        """Local index candidates for a distributed filtered read
        (replica side of ReplicaFilteringProtection: each queried
        replica contributes ITS view of matching locators; the
        coordinator re-reads every candidate at the read CL and
        re-checks the predicate, so stale local matches are dropped and
        matches another replica missed are found)."""
        keyspace, table_name, col, op, value = msg.payload
        registry = getattr(self.engine, "indexes", None)
        idx = registry.get(keyspace, table_name, col) \
            if registry is not None else None
        locators: list = []
        if idx is not None:
            if op == "=" and hasattr(idx, "lookup"):
                locators = list(idx.lookup(value))
            elif op == "LIKE" and hasattr(idx, "search"):
                locators = list(idx.search(str(value)) or [])
            elif op == "ANN" and hasattr(idx, "ann"):
                import numpy as np
                q, k = value
                locators = [(pk, ck, float(score)) for pk, ck, score in
                            idx.ann(np.asarray(q, dtype=np.float32),
                                    int(k))]
        return Verb.INDEX_RSP, locators

    def _handle_truncate(self, msg):
        keyspace, table_name = msg.payload
        store = self.engine.store(keyspace, table_name)
        store.truncate()
        self.counters.invalidate_table(store.table.id)
        return Verb.TRUNCATE_RSP, b""

    # ----------------------------------------------------- cluster telemetry

    def telemetry_snapshot(self) -> dict:
        """One node's ENGINE-scoped telemetry — the METRICS_SNAPSHOT_RSP
        payload behind `nodetool clusterstats`: tpstats, compaction
        gauges, per-table counters + amplification, the SLO snapshot
        and messaging counters. Engine-scoped on purpose: in-process
        clusters share the process-global metrics registry, so a
        cluster view built from global counters would show every node
        the same numbers."""
        from ..tools.nodetool import tpstats
        eng = self.engine
        tables = {}
        writes = 0
        for cfs in list(eng.stores.values()):
            live = cfs.live_sstables()
            writes += cfs.metrics.get("writes", 0)
            tables[cfs.table.full_name()] = {
                **{k: int(v) for k, v in cfs.metrics.items()},
                **cfs.amplification(),
                "sstables": len(live),
                "live_bytes": sum(s.size_bytes for s in live),
            }
        return {
            "endpoint": self.endpoint.name,
            "at_ms": int(time.time() * 1000),
            "tpstats": tpstats(eng),
            "compactions": eng.compactions.gauges(),
            "tables": tables,
            "storage_writes": writes,
            "write_stalls": eng.write_stalls,
            "slo": eng.slo.snapshot(),
            "messaging": dict(self.messaging.metrics),
        }

    def _handle_metrics_snapshot(self, msg):
        return Verb.METRICS_SNAPSHOT_RSP, self.telemetry_snapshot()

    def pull_cluster_telemetry(self, timeout: float = 2.0) -> dict:
        """Pull every peer's telemetry snapshot over the
        METRICS_SNAPSHOT verb (the local node serves itself directly).
        Bounded: a peer that does not answer within `timeout` is
        reported with its LAST successfully-pulled snapshot and a
        staleness stamp — or no snapshot at all if it was never heard
        from — so a dark node can never hang the pull. The response
        callbacks only record the payload and signal an event; nothing
        blocking ever runs on the messaging dispatch worker."""
        cache = self._peer_telemetry
        peers = [ep for ep in list(self.ring.endpoints)
                 if ep != self.endpoint]
        done = threading.Event()
        state = {"pending": len(peers)}
        lock = threading.Lock()

        def _one_done():
            with lock:
                state["pending"] -= 1
                if state["pending"] <= 0:
                    done.set()

        t_pull = time.monotonic()
        for ep in peers:
            def on_rsp(msg, _ep=ep):
                cache[_ep.name] = (msg.payload, time.monotonic())
                _one_done()

            def on_fail(_arg, _ep=ep):
                _one_done()

            self.messaging.send_with_callback(
                Verb.METRICS_SNAPSHOT_REQ, b"", ep,
                on_rsp, on_failure=on_fail, timeout=timeout)
        if peers:
            # margin covers the reaper's 100 ms expiry granularity
            done.wait(timeout + 1.0)
        rows = [{"endpoint": self.endpoint.name, "alive": True,
                 "fresh": True, "stale_s": 0.0,
                 "snapshot": self.telemetry_snapshot()}]
        now = time.monotonic()
        for ep in peers:
            entry = cache.get(ep.name)
            rows.append({
                "endpoint": ep.name,
                "alive": self.is_alive(ep),
                "fresh": entry is not None and entry[1] >= t_pull,
                "stale_s": (None if entry is None
                            else round(now - entry[1], 3)),
                "snapshot": entry[0] if entry is not None else None,
            })
        return {"nodes": rows,
                "pulled_at_ms": int(time.time() * 1000)}

    # ---------------------------------------------------------- liveness --

    def is_alive(self, ep: Endpoint) -> bool:
        return ep == self.endpoint or self.gossiper.is_alive(ep)

    def add_event_listener(self, fn) -> None:
        """fn(kind, info): kind in STATUS_CHANGE / TOPOLOGY_CHANGE /
        SCHEMA_CHANGE (the native protocol's registerable events)."""
        self._event_listeners.append(fn)

    def remove_event_listener(self, fn) -> None:
        try:
            self._event_listeners.remove(fn)
        except ValueError:
            pass

    def emit_event(self, kind: str, info: dict) -> None:
        for fn in list(self._event_listeners):
            try:
                fn(kind, info)
            except Exception:
                pass

    def _on_peer_alive(self, ep: Endpoint):
        self.emit_event("STATUS_CHANGE", {"change": "UP", "host": ep.host,
                                          "port": ep.port})
        self._dispatch_hints(ep)

    def _on_peer_dead(self, ep: Endpoint):
        self.emit_event("STATUS_CHANGE", {"change": "DOWN",
                                          "host": ep.host,
                                          "port": ep.port})

    def _on_storage_failure(self, err) -> None:
        """A `stop`/`die` failure policy tripped: transition out of the
        ring. Own gossip status flips to shutdown and the gossiper
        stops speaking — peers convict via phi accrual exactly as they
        would for a dead process (the reference stops gossip and the
        client transports; the admin/CQL servers here check the same
        failure gates on every request)."""
        g = self.gossiper
        with g._lock:
            st = g.states.get(self.endpoint)
            if st is not None:
                st.app_states["status"] = "shutdown"
                st.version += 1
        g.stop()
        self.emit_event("STATUS_CHANGE", {"change": "DOWN",
                                          "host": self.endpoint.host,
                                          "port": self.endpoint.port})

    def _hint_loop(self):
        while not self._stop_hints.wait(0.5):
            try:
                self.hint_round()
            except Exception:
                # replay I/O errors are handled (and counted) inside
                # hint_round per target; anything escaping here is a
                # bug that must not silently end hint dispatch for the
                # node's lifetime (ctpulint worker-loops)
                self.hints.metrics["dispatch_failures"] = \
                    self.hints.metrics.get("dispatch_failures", 0) + 1

    def hint_round(self) -> None:
        """One hint-dispatch pass (extracted so the deterministic
        simulator can drive it as a timer instead of a thread). Self
        included: a failed local apply (e.g. as a pending replica)
        leaves a self-hint that replays through the transport
        loopback."""
        for ep in list(self.ring.endpoints) + [self.endpoint]:
            if self.hints.has_hints(ep) and self.is_alive(ep):
                try:
                    self._dispatch_hints(ep)
                except Exception:
                    pass

    def _dispatch_hints(self, ep: Endpoint):
        """Replay hints with acks: un-acked mutations are re-stored so a
        still-unreachable target keeps its hints."""
        if not self.hints.has_hints(ep):
            return

        def send(m):
            self.messaging.send_with_callback(
                Verb.HINT_REQ, m.serialize(), ep,
                on_response=lambda rsp: None,
                on_failure=lambda mid, mm=m: self.hints.store(
                    ep, mm, redelivery=True),
                timeout=self.proxy.timeout)

        self.hints.dispatch(ep, send)

    # -------------------------------------------------- CQL backend role --

    @property
    def indexes(self):
        return getattr(self.engine, "indexes", None)

    @property
    def triggers(self):
        return getattr(self.engine, "triggers", None)

    @property
    def monitor(self):
        return getattr(self.engine, "monitor", None)

    def apply(self, mutation: Mutation, durable: bool = True) -> None:
        t = self.schema.table_by_id(mutation.table_id)
        if t is None:
            raise KeyError(f"unknown table id {mutation.table_id}")
        from ..storage.cellbatch import FLAG_COUNTER
        if any(op[7] & FLAG_COUNTER for op in mutation.ops):
            # increments are not idempotent: route through the counter
            # leader (cluster/counters.py), never the plain write path
            self.counters.mutate(t.keyspace, mutation, self.default_cl)
        else:
            self.proxy.mutate(t.keyspace, mutation, self.default_cl)

    def store(self, keyspace: str, name: str):
        return _DistributedStore(self, keyspace, name)

    def add_table(self, t):
        # shared-schema round 1: every node opens a store for the table
        # (distributed schema agreement lands with the cluster-metadata log)
        for node in self.cluster_nodes:
            node.engine._open_store(t)
        self.schema.add_table(t)

    def drop_table(self, keyspace: str, name: str):
        t = self.schema.get_table(keyspace, name)
        for node in self.cluster_nodes:
            cfs = node.engine.stores.pop(t.id, None)
            if cfs:
                cfs.truncate()
            node.counters.invalidate_table(t.id)
        self.schema.drop_table(keyspace, name)

    cluster_nodes: list = ()
    schema_sync = None   # TCM-lite DDL replication (cluster/schema_sync)

    def session(self) -> Session:
        return Session(self)

    # ------------------------------------------------- topology changes --

    def topology_commit(self, extra: dict) -> None:
        """Commit one topology transformation. TCP clusters route it
        through the epoch log (every node applies the same entries in
        the same order — tcm/Commit); LocalCluster nodes share one Ring
        object, so the transformation applies directly."""
        from .schema_sync import apply_topology_to_ring, \
            emit_topology_event
        if self.schema_sync is not None:
            self.schema_sync.commit_topology(extra)
        else:
            apply_topology_to_ring(self.ring, extra)
            # in-process path: peers share the ring object, so each node
            # emits its own driver-facing event here
            for n in (self.cluster_nodes or [self]):
                emit_topology_event(n, extra)

    def _ep_dict(self, ep: Endpoint | None = None) -> dict:
        ep = ep or self.endpoint
        return {"name": ep.name, "dc": ep.dc, "rack": ep.rack,
                "host": ep.host, "port": ep.port}

    def join_cluster(self, tokens: list[int]) -> int:
        """Full TCM join sequence (tcm/sequences/BootstrapAndJoin):
        start_join (tokens pending, writes duplicated) -> stream ->
        finish_join (ownership flip). Resumable: a crash between the
        two entries leaves start_join in the log; resume_topology()
        on restart re-streams and commits the finish."""
        self.topology_commit({"op": "start_join", "node": self._ep_dict(),
                              "tokens": [int(t) for t in tokens]})
        try:
            streamed = self.bootstrap()
        except BaseException:
            self.topology_commit({"op": "abort_join",
                                  "node": self._ep_dict()})
            raise
        self.topology_commit({"op": "finish_join",
                              "node": self._ep_dict()})
        return streamed

    def move_tokens(self, new_tokens: list[int]) -> int:
        """nodetool move (tcm/sequences/Move): gained ranges stream IN
        from their current owners (pending-write duplication active
        meanwhile); after the flip, data of surrendered ranges streams
        OUT to its new owners, acked, before this returns."""
        from ..storage.cellbatch import filter_token_range
        from .replication import ReplicationStrategy
        me = self.endpoint
        old_tokens = [int(t) for t in self.ring.endpoints[me]]
        new_tokens = [int(t) for t in new_tokens]
        self.topology_commit({"op": "start_move", "node": self._ep_dict(),
                              "tokens": new_tokens})
        try:
            streamed = self.bootstrap()
            # ranges this node stops replicating once old tokens release
            # (the future ring IS the post-move ring: moving tokens are
            # excluded from it, so racing writes to surrendered ranges
            # are already duplicated to their gaining owners)
            after = self.ring.future_ring()
            outgoing = []
            for ks in list(self.schema.keyspaces.values()):
                strat = ReplicationStrategy.create(ks.params.replication)
                lost_arcs = []
                for lo, hi in self.ring.all_ranges():
                    if me in strat.replicas(self.ring, hi) and \
                            me not in strat.replicas(after, hi):
                        lost_arcs += [(-(1 << 63), hi),
                                      (lo, (1 << 63) - 1)] \
                            if lo > hi else [(lo, hi)]
                if not lost_arcs:
                    continue
                for tname, table in ks.tables.items():
                    allb = self.engine.store(ks.name, tname).scan_all()
                    for alo, ahi in lost_arcs:
                        part = filter_token_range(allb, alo, ahi)
                        if len(part):
                            outgoing.append((ks.name, table, part))
            # push surrendered data BEFORE the flip, routed by the
            # post-move ring: a crash here leaves start_move in the log
            # and the resume re-runs the whole (idempotent) sequence —
            # pushing after the flip would lose the handoff on a crash
            # between the two
            for ksn, table, part in outgoing:
                self.repair.apply_batch_to_owners(ksn, table, part,
                                                  ring=after)
                streamed += len(part)
        except BaseException:
            self.topology_commit({"op": "abort_move",
                                  "node": self._ep_dict()})
            raise
        self.topology_commit({"op": "finish_move", "node": self._ep_dict(),
                              "old_tokens": old_tokens})
        return streamed

    def replace_node(self, dead_name: str) -> int:
        """Replace a DEAD node: this (new, empty) node assumes its
        tokens, streaming every replica range from the survivors
        (reference replace_address flow / tcm/sequences startup
        Replace). The dead node must be convicted down; writes during
        the replace are duplicated here via the future ring."""
        dead = next((e for e in self.ring.endpoints
                     if e.name == dead_name), None)
        if dead is None:
            raise ValueError(f"{dead_name} not in ring")
        # positive evidence of death required: a fresh node has no
        # gossip state at all, and "never heard of it" must not license
        # removing a live member (split-brain); the operator/harness
        # conveys conviction via force_convict or observed heartbeats
        st = self.gossiper.states.get(dead)
        if st is None or st.alive:
            raise ValueError(f"{dead_name} is alive or of unknown "
                             f"liveness; replace requires the failure "
                             f"detector to have convicted it")
        self.topology_commit({"op": "start_replace",
                              "node": self._ep_dict(),
                              "target": dead_name})
        try:
            streamed = self.bootstrap()
        except BaseException:
            self.topology_commit({"op": "abort_replace",
                                  "node": self._ep_dict()})
            raise
        self.topology_commit({"op": "finish_replace",
                              "node": self._ep_dict()})
        return streamed

    def resume_topology(self) -> int | None:
        """Resume a multi-step topology operation this node crashed in
        the middle of (the epoch log holds the start_* entry; the
        finish never committed). Returns cells streamed, or None if
        nothing was pending. Reference: TCM in-progress sequences are
        resumed from the log at startup (tcm/Startup, MultiStepOperation)."""
        me = self.endpoint
        if me in self.ring.pending:
            if me in self.ring.endpoints:    # interrupted token MOVE
                new_tokens = [int(t) for t in self.ring.pending[me]]
                # abort cluster-wide, then re-run the whole sequence at
                # fresh epochs: streaming is idempotent (timestamp
                # reconcile dedups re-streamed cells), so repeating is safe
                self.topology_commit({"op": "abort_move",
                                      "node": self._ep_dict()})
                return self.move_tokens(new_tokens)
            streamed = self.bootstrap()
            self.topology_commit({"op": "finish_join",
                                  "node": self._ep_dict()})
            return streamed
        if me in self.ring.replacing:
            streamed = self.bootstrap()
            self.topology_commit({"op": "finish_replace",
                                  "node": self._ep_dict()})
            return streamed
        return None

    def bootstrap(self) -> int:
        """Pull this node's replica ranges from existing owners and write
        them as local sstables (reference: tcm/sequences/BootstrapAndJoin
        -> RangeStreamer -> entire-sstable streaming). Preferred flow:
        ring.add_pending(me) -> bootstrap() -> ring.promote_pending(me):
        reads keep hitting the old owners while writes are duplicated to
        this node (coordinator pending targets), so nothing is lost OR
        prematurely served. Returns cells streamed. Also supports the
        legacy already-in-ring flow (sources computed from a pre-join
        clone)."""
        from .replication import ReplicationStrategy

        total = 0
        if self.endpoint in self.ring.pending or \
                self.endpoint in self.ring.replacing:
            future = self.ring.future_ring()
            current = self.ring    # the PRE-change ring: stream sources
        else:
            future = self.ring
            current = self.ring.clone_without(self.endpoint)
        for ks in list(self.schema.keyspaces.values()):
            strat = ReplicationStrategy.create(ks.params.replication)
            for lo, hi in future.all_ranges():
                replicas = strat.replicas(future, hi)
                if self.endpoint not in replicas:
                    continue   # we don't replicate this range
                cur_replicas = strat.replicas(current, hi)
                if self.endpoint in cur_replicas:
                    continue   # already a replica (token move keeps it)
                owners = [e for e in cur_replicas
                          if e != self.endpoint and self.is_alive(e)]
                if not owners:
                    if any(e != self.endpoint for e in cur_replicas):
                        # the range HAS owners but none is live: silently
                        # skipping would let the join/replace "complete"
                        # with zero data and serve empty reads — fail the
                        # sequence instead (the caller aborts and the
                        # operator retries when sources are up)
                        raise RuntimeError(
                            f"no live stream source for range "
                            f"({lo}, {hi}] of {ks.name} "
                            f"(owners: {cur_replicas})")
                    continue   # genuinely unowned (empty pre-ring)
                for tname, table in ks.tables.items():
                    arcs = [(-(1 << 63), hi),
                            (lo, (1 << 63) - 1)] if lo > hi else [(lo, hi)]
                    for alo, ahi in arcs:
                        # sessioned entire-sstable streaming: whole
                        # in-range sstables arrive as chunked component
                        # FILES (zero re-serialization, attached indexes
                        # included) and land atomically (TOC last);
                        # only boundary-straddling data re-serializes.
                        # The session is resumable and throttled — a
                        # big join no longer rides one giant message
                        res = self.streams.stream_range(
                            owners[0], ks.name, tname, alo, ahi,
                            timeout=max(self.proxy.timeout, 30.0))
                        total += int(res["cells"])
        return total

    def decommission(self) -> int:
        """Stream every locally-replicated range to the owners that GAIN
        it once this node leaves, then leave the ring (tcm/sequences/
        Leave + unbootstrap streaming role). The "push" is modelled as a
        remote pull (STREAM_PULL_REQ): each gaining owner runs a
        receiver session against this node, so the transfer is chunked,
        throttled and atomically landed like any other session — and
        the mover's landing is local on the gaining side."""
        from .replication import ReplicationStrategy
        me = self.endpoint
        future = self.ring.clone_without(me)
        total = 0
        for ks in list(self.schema.keyspaces.values()):
            strat = ReplicationStrategy.create(ks.params.replication)
            # iterate the CURRENT ring's ranges: each maps into exactly
            # one future range (the future ring merges ours), so the
            # gained-replica set is constant across a current range —
            # the future ring's coarser ranges would NOT give constant
            # current-replica sets and could skip data
            for lo, hi in self.ring.all_ranges():
                cur = strat.replicas(self.ring, hi)
                if me not in cur:
                    continue
                fut = strat.replicas(future, hi)
                gained = [e for e in fut
                          if e not in cur and self.is_alive(e)]
                if not gained:
                    continue
                arcs = [(-(1 << 63), hi),
                        (lo, (1 << 63) - 1)] if lo > hi else [(lo, hi)]
                for tname in ks.tables:
                    for ep in gained:
                        for alo, ahi in arcs:
                            res = self.streams.request_pull(
                                ep, ks.name, tname, alo, ahi,
                                max(self.proxy.timeout, 35.0))
                            total += int(res.get("cells", 0))
        self.ring.remove_node(me)   # new ownership takes effect
        self.shutdown()
        return total

    def shutdown(self):
        self._stop_hints.set()
        self.counters.close()
        self.streams.close()
        self.gossiper.stop()
        self.messaging.close()
        for cfg_name, cb_ in getattr(self.proxy, "_settings_subs", []):
            self.engine.settings.remove_listener(cfg_name, cb_)
        for cfg_name, cb_ in getattr(self, "_settings_subs", []):
            self.engine.settings.remove_listener(cfg_name, cb_)
        self.engine.close()


class _DistributedStore:
    """Read facade the CQL executor uses; routes through the coordinator."""

    def __init__(self, node: Node, keyspace: str, name: str):
        self.node = node
        self.keyspace = keyspace
        self.name = name

    def read_partition(self, pk: bytes, now=None, limits=None):
        return self.node.proxy.read_partition(self.keyspace, self.name, pk,
                                              self.node.default_cl,
                                              limits=limits)

    def scan_all(self, now=None):
        return self.node.proxy.scan_all(self.keyspace, self.name,
                                        self.node.default_cl)

    def scan_window(self, lo: int, hi: int, now=None, limits=None):
        return self.node.proxy.scan_window(self.keyspace, self.name, lo,
                                           hi, self.node.default_cl,
                                           limits=limits)

    def iter_scan(self, now=None, after: int = -(1 << 63),
                  window_parts: int = 64, limits=None):
        """Bounded cluster scan: one vnode arc per window, each fetched
        from that arc's replicas only (paging substrate; window_parts is
        a partition-count hint the arc granularity stands in for)."""
        MIN, MAX = -(1 << 63), (1 << 63) - 1
        bounds = sorted({hi for _, hi in self.node.ring.all_ranges()})
        cuts = [b for b in bounds if b > after] + [MAX]
        pos = after
        for hi in cuts:
            if hi <= pos and not (pos == MIN and hi == MIN):
                continue
            batch = self.scan_window(pos, hi, now, limits=limits)
            if len(batch):
                yield batch
            pos = hi
            if pos == MAX:
                break

    def truncate(self):
        for ep in list(self.node.ring.endpoints):
            if ep == self.node.endpoint:
                store = self.node.engine.store(self.keyspace, self.name)
                store.truncate()
                self.node.counters.invalidate_table(store.table.id)
            else:
                self.node.messaging.send_one_way(
                    Verb.TRUNCATE_REQ, (self.keyspace, self.name), ep)


class LocalCluster:
    """N in-process nodes sharing a transport with fault injection
    (the jvm-dtest Cluster)."""

    def __init__(self, n: int, base_dir: str, rf: int = 3,
                 gossip_interval: float = 0.05,
                 dcs: list[str] | None = None):
        self.base_dir = base_dir
        self.transport = LocalTransport()
        self.schema = Schema()
        self.ring = Ring()
        self.nodes: list[Node] = []
        self._stopped: set[int] = set()
        endpoints = []
        tokens = even_tokens(n, vnodes=4)
        for i in range(n):
            dc = dcs[i] if dcs else "dc1"
            ep = Endpoint(f"node{i + 1}", dc=dc)
            endpoints.append(ep)
            self.ring.add_node(ep, tokens[i])
        for i, ep in enumerate(endpoints):
            node = Node(ep, os.path.join(base_dir, ep.name), self.schema,
                        self.ring, self.transport, seeds=endpoints[:1],
                        gossip_interval=gossip_interval)
            self.nodes.append(node)
        from .gossip import EndpointState
        for node in self.nodes:
            node.cluster_nodes = self.nodes
            # seed full liveness so tests don't wait for convergence
            for other in self.nodes:
                if other.endpoint != node.endpoint:
                    st = node.gossiper.states.setdefault(
                        other.endpoint, EndpointState(generation=1))
                    node.gossiper.detector.report(
                        other.endpoint, st, node.gossiper.clock())
        for node in self.nodes:
            node.gossiper.start()

    @property
    def filters(self):
        return self.transport.filters

    def node(self, i: int) -> Node:
        return self.nodes[i - 1]

    def session(self, i: int = 1) -> Session:
        return self.nodes[i - 1].session()

    def add_node(self, dc: str = "dc1", vnodes: int = 4,
                 mid_join_hook=None) -> Node:
        """Grow the cluster: register the new node's tokens as PENDING,
        bootstrap-stream from the pre-join owners (writes arriving
        meanwhile are duplicated to the joining node), then promote to
        full ownership (the jvm-dtest addInstance + BootstrapAndJoin
        flow). mid_join_hook() runs between the pending registration and
        the ownership flip — tests inject concurrent writes there."""
        from .ring import Endpoint, allocate_tokens
        i = len(self.nodes) + 1
        ep = Endpoint(f"node{i}", dc=dc)
        # balanced growth: bisect the widest current ranges
        # (dht/tokenallocator role)
        tokens = allocate_tokens(self.ring, vnodes)
        node = Node(ep, os.path.join(self.base_dir, ep.name), self.schema,
                    self.ring, self.transport,
                    seeds=[self.nodes[0].endpoint],
                    gossip_interval=self.nodes[0].gossiper.interval)
        node.cluster_nodes = self.nodes
        from .gossip import EndpointState
        # seed liveness both ways
        for other in self.nodes:
            node.gossiper.states.setdefault(other.endpoint,
                                            EndpointState(generation=1))
            node.gossiper.detector.report(
                other.endpoint,
                node.gossiper.states[other.endpoint],
                node.gossiper.clock())
            other.gossiper.states.setdefault(ep, EndpointState(generation=1))
            other.gossiper.detector.report(
                ep, other.gossiper.states[ep], other.gossiper.clock())
        self.ring.add_pending(ep, tokens)
        try:
            node.bootstrap()
            if mid_join_hook is not None:
                mid_join_hook()
            self.ring.promote_pending(ep)
        except BaseException:
            self.ring.cancel_pending(ep)
            # tear the half-created node down fully: engine/commitlog
            # handles, transport registration, and peers' liveness seeds
            node._stop_hints.set()
            node.gossiper.stop()
            node.messaging.close()
            node.engine.close()
            for other in self.nodes:
                other.gossiper.states.pop(ep, None)
            raise
        self.nodes.append(node)
        node.gossiper.start()
        return node

    def move_node(self, i: int, new_tokens: list[int]) -> int:
        """nodetool move on node i (see Node.move_tokens)."""
        return self.nodes[i - 1].move_tokens(new_tokens)

    def replace_dead_node(self, dead_i: int, dc: str = "dc1") -> Node:
        """Replace a stopped node with a fresh one that assumes its
        tokens (replace_address flow). The dead node must already be
        stopped (stop_node); its Node object stays in self.nodes so
        tests can inspect it, but it is out of the ring afterwards."""
        from .gossip import EndpointState
        dead = self.nodes[dead_i - 1]
        if dead_i not in self._stopped:
            raise ValueError(f"{dead.endpoint.name} is alive; "
                             f"decommission it instead of replacing")
        i = len(self.nodes) + 1
        ep = Endpoint(f"node{i}", dc=dc)
        seeds = [n.endpoint for n in self.nodes
                 if n.endpoint != dead.endpoint][:1]
        node = Node(ep, os.path.join(self.base_dir, ep.name), self.schema,
                    self.ring, self.transport, seeds=seeds,
                    gossip_interval=self.nodes[0].gossiper.interval)
        node.cluster_nodes = self.nodes
        # the dead peer must be CONVICTED everywhere before a replace
        # (the reference requires the FD to see it down): pin its known
        # (generation, version) so silent digests can't resurrect it
        dead_st = self.nodes[0].gossiper.states.get(dead.endpoint)
        dgen = dead_st.generation if dead_st else 1
        dver = dead_st.version if dead_st else 0
        node.gossiper.force_convict(dead.endpoint, dgen, dver)
        for other in self.nodes:
            if other.endpoint == dead.endpoint:
                continue
            other.gossiper.force_convict(dead.endpoint)
            node.gossiper.states.setdefault(other.endpoint,
                                            EndpointState(generation=1))
            node.gossiper.detector.report(
                other.endpoint, node.gossiper.states[other.endpoint],
                node.gossiper.clock())
            other.gossiper.states.setdefault(ep, EndpointState(generation=1))
            other.gossiper.detector.report(
                ep, other.gossiper.states[ep], other.gossiper.clock())
        try:
            node.replace_node(dead.endpoint.name)
        except BaseException:
            node._stop_hints.set()
            node.gossiper.stop()
            node.messaging.close()
            node.engine.close()
            raise
        self.nodes.append(node)
        node.gossiper.start()
        return node

    def stop_node(self, i: int) -> None:
        """Simulate a crash: stop gossip + messaging + hint dispatch
        (a crashed process sends nothing; data stays on disk)."""
        n = self.nodes[i - 1]
        self._stopped.add(i)
        n._stop_hints.set()
        n.streams.close()   # in-flight sessions die; durable state stays
        n.gossiper.stop()
        n.messaging.close()

    def restart_node(self, i: int) -> None:
        import threading
        self._stopped.discard(i)
        n = self.nodes[i - 1]
        n.messaging = MessagingService(n.endpoint, self.transport)
        _settings = getattr(n.engine, "settings", None)
        if _settings is not None:
            n.messaging.set_dispatch_workers(
                int(_settings.get("internode_dispatch_threads")))
        n.gossiper = Gossiper(n.messaging, [self.nodes[0].endpoint],
                              interval=n.gossiper.interval)
        n.gossiper.on_alive = n._on_peer_alive
        # re-seed peer liveness into the fresh detector (same both-ways
        # seeding as startup/add_node): without it the restarted node
        # convicts every peer until gossip rounds catch up and refuses
        # to coordinate QUORUM traffic from its still-open CQL server
        from .gossip import EndpointState
        down = {self.nodes[j - 1].endpoint for j in self._stopped}
        for other in self.nodes:
            if other is n or other.endpoint in down:
                continue
            st = n.gossiper.states.setdefault(other.endpoint,
                                              EndpointState(generation=1))
            n.gossiper.detector.report(other.endpoint, st,
                                       n.gossiper.clock())
        n._register_verbs()
        n.proxy = StorageProxy(n)
        # re-register sidecar verb handlers on the fresh MessagingService
        # (paxos state resets too — crash semantics; promises are volatile)
        from .counters import CounterService
        from .paxos import PaxosService
        from .repair import RepairService
        from .streaming import StreamService
        n.paxos = PaxosService(n)
        n.repair = RepairService(n)
        n.counters.close()
        n.counters = CounterService(n)
        n.streams.close()
        n.streams = StreamService(n)
        n.gossiper.start()
        n._stop_hints = threading.Event()
        n._hint_thread = threading.Thread(target=n._hint_loop, daemon=True)
        n._hint_thread.start()

    def shutdown(self):
        for n in self.nodes:
            try:
                n.shutdown()
            except Exception:
                pass
