"""Audit / full query logging.

Reference counterpart: audit/AuditLogManager.java (category-filtered
audit records) + fql/FullQueryLogger.java (every request, replayable).
One JSONL stream covers both roles here: each record carries timestamp,
user, keyspace, statement category and the query string; `categories`
filters like the reference's included_categories.

Enable per engine: StorageEngine(..., audit_log_path=...) or at runtime
via engine.audit_log = AuditLog(path).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time

_PASSWORD_RE = re.compile(r"(password\s*=\s*)'(?:[^']|'')*'", re.I)
# prepared-statement form: the credential arrives as a BIND VALUE
# ("... WITH password = ?"), so scrubbing the statement text alone
# leaks it through the params list — any statement matching this
# pattern gets EVERY bind value redacted (cheap and safe: password-
# bearing statements are DCL, never data-path hot)
_PASSWORD_BIND_RE = re.compile(r"password\s*=\s*(\?|:\w+)", re.I)

CATEGORY_OF = {
    "SelectStatement": "QUERY",
    "InsertStatement": "DML", "UpdateStatement": "DML",
    "DeleteStatement": "DML", "BatchStatement": "DML",
    "TruncateStatement": "DML",
    "CreateKeyspaceStatement": "DDL", "CreateTableStatement": "DDL",
    "CreateIndexStatement": "DDL", "CreateTypeStatement": "DDL",
    "CreateViewStatement": "DDL", "DropStatement": "DDL",
    "AlterTableStatement": "DDL",
    "RoleStatement": "DCL", "GrantStatement": "DCL",
    "ListRolesStatement": "DCL",
    "UseStatement": "OTHER",
}


class AuditLog:
    def __init__(self, path: str, categories: set[str] | None = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.categories = categories    # None = everything (FQL mode)
        self._lock = threading.Lock()
        self._f = open(path, "a")

    def log(self, stmt_type: str, query: str, user: str | None,
            keyspace: str | None, params=None) -> None:
        category = CATEGORY_OF.get(stmt_type, "OTHER")
        if self.categories is not None \
                and category not in self.categories:
            return
        # credentials never reach the log (the reference obfuscates
        # passwords in audit/FQL records)
        query = _PASSWORD_RE.sub(r"\1'***'", query)
        rec = {"ts_ms": int(time.time() * 1000), "category": category,
               "type": stmt_type, "user": user, "keyspace": keyspace,
               "query": query}
        if params:
            if _PASSWORD_BIND_RE.search(query):
                # a prepared EXECUTE carries the credential as a bind
                # value — redact them all, mirroring the text scrub
                rec["params"] = ["***"] * len(params)
            else:
                rec["params"] = [p.hex()
                                 if isinstance(p, (bytes, bytearray))
                                 else repr(p) for p in
                                 (params.values()
                                  if isinstance(params, dict)
                                  else params)]
        line = json.dumps(rec) + "\n"
        from .metrics import GLOBAL
        try:
            with self._lock:
                self._f.write(line)
                self._f.flush()
        except (OSError, ValueError):
            # a wedged/closed log file must be OBSERVABLE, not fatal to
            # the request: audit.dropped vs audit.records is the gap an
            # operator alerts on
            GLOBAL.incr("audit.dropped")
            return
        GLOBAL.incr("audit.records")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()
