"""Counter writes: the leader path.

Reference counterpart: service/StorageProxy.java applyCounterMutation +
db/CounterMutation.java (striped locks, read-modify-write into per-node
shards, the counter write stage). A counter increment is NOT
idempotent, so it cannot fan out like a normal write — a retried or
hinted delta would double-count. Instead:

  1. the coordinator routes the increment to a LEADER: a live replica
     of the key (itself when it is one);
  2. the leader serializes increments per partition (striped locks),
     reads its OWN current shard for each touched counter column, and
     folds the delta into a CUMULATIVE per-leader shard cell:
     path = leader name, value = running total, timestamp strictly
     monotonic per shard;
  3. the shard cell replicates through the NORMAL write path at the
     requested consistency level. Shards are plain last-write-wins
     cells (no FLAG_COUNTER — cumulative totals must never be summed
     across versions), so retries, hints, read repair and all three
     merge engines handle them with zero special cases.

A counter column's read value is the SUM of its live shards — one per
leader that ever coordinated an increment for it — summed during row
assembly (storage/rows.py). The non-cluster engine path keeps plain
delta cells (path=b"", FLAG_COUNTER, merge sums them); shard identity
only matters once increments replicate.
"""
from __future__ import annotations

import threading
import zlib
from concurrent.futures import ThreadPoolExecutor

from ..storage.cellbatch import FLAG_COUNTER, FLAG_TOMBSTONE
from ..storage.mutation import Mutation
from ..utils import timeutil
from .messaging import Verb


class CounterService:
    STRIPES = 64
    CACHE_MAX = 65536   # own-shard entries (counter cache role)

    def __init__(self, node):
        self.node = node
        self._locks = [threading.Lock() for _ in range(self.STRIPES)]
        # counter cache (cache/CounterCacheKey role): this node's OWN
        # shard per touched counter cell. Coherent because only
        # apply_as_leader writes our shard, serialized by the stripe
        # locks — without it every increment pays a full partition read.
        self._cache: dict[tuple, tuple[int, int]] = {}
        self._cache_lock = threading.Lock()
        self._cache_epoch = 0   # bumped by invalidate_table (truncate)
        # the counter write stage: leader-side work blocks on the
        # replication CL, so it must NEVER run on the messaging
        # dispatch thread (the acks it waits for arrive there)
        self._stage = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"counter-{node.endpoint.name}")
        node.messaging.register_handler(Verb.COUNTER_REQ, self._handle)

    def close(self) -> None:
        self._stage.shutdown(wait=False)

    def _lock_for(self, pk: bytes) -> threading.Lock:
        return self._locks[zlib.crc32(pk) % self.STRIPES]

    def invalidate_table(self, table_id) -> None:
        """TRUNCATE/DROP: cached shard totals for the table must not
        survive (they would resurrect pre-truncate counts). The epoch
        bump makes an in-flight apply_as_leader discard its pending
        cache insert — its shard was computed against pre-truncate
        state."""
        with self._cache_lock:
            self._cache_epoch += 1
            for k in [k for k in self._cache if k[0] == table_id]:
                del self._cache[k]

    def invalidate_cache(self) -> None:
        """nodetool invalidatecountercache: drop every cached shard."""
        with self._cache_lock:
            self._cache_epoch += 1
            self._cache.clear()

    # ------------------------------------------------------------ leader --

    def apply_as_leader(self, keyspace: str, mutation: Mutation,
                        cl: str) -> None:
        """Fold delta ops into this node's cumulative shards, then
        replicate the shard mutation at `cl`. Runs on a client thread
        or the counter stage — never the dispatch thread."""
        t = self.node.schema.table_by_id(mutation.table_id)
        cfs = self.node.engine.store(t.keyspace, t.name)
        shard_path = self.node.endpoint.name.encode()
        with self._lock_for(mutation.pk):
            current = None        # partition read only on cache miss
            shard_m = Mutation(mutation.table_id, mutation.pk)
            now = timeutil.now_micros()
            deltas: dict[tuple, int] = {}
            for ck, column, path, value, ts, ldt, ttl, flags in \
                    mutation.ops:
                if flags & FLAG_COUNTER:
                    key = (ck, column)
                    deltas[key] = deltas.get(key, 0) + int.from_bytes(
                        value, "big", signed=True)
                else:
                    shard_m.add(ck, column, path, value, ts, ldt, ttl,
                                flags)
            new_cache = {}
            with self._cache_lock:
                epoch0 = self._cache_epoch
            for (ck, column), delta in deltas.items():
                ckey = (mutation.table_id, mutation.pk, ck, column)
                with self._cache_lock:
                    hit = self._cache.get(ckey)
                if hit is None:
                    if current is None:
                        current = cfs.read_partition(mutation.pk)
                    hit = self._own_shard(current, ck, column,
                                          shard_path)
                old_sum, old_ts = hit
                ts = max(now, old_ts + 1)
                shard_m.add(ck, column, shard_path,
                            (old_sum + delta).to_bytes(8, "big",
                                                       signed=True), ts)
                new_cache[ckey] = (old_sum + delta, ts)
            try:
                self.node.proxy.mutate(t.keyspace, shard_m, cl)
            except Exception:
                # the shard may have applied to SOME replicas (e.g. a
                # timeout after the local write): stale cache entries
                # would roll those shards backwards on the next
                # increment — evict so it re-reads local truth
                with self._cache_lock:
                    for ckey in new_cache:
                        self._cache.pop(ckey, None)
                raise
            with self._cache_lock:
                if self._cache_epoch != epoch0:
                    return   # truncated mid-flight: don't resurrect
                if len(self._cache) + len(new_cache) > self.CACHE_MAX:
                    self._cache.clear()
                self._cache.update(new_cache)

    @staticmethod
    def _own_shard(batch, ck: bytes, column: int,
                   shard_path: bytes) -> tuple[int, int]:
        """(current total, timestamp) of this leader's shard in the
        reconciled local partition view; (0, 0) if never written.
        Lane-array prefilter keeps this O(matching cells) in Python —
        the full-partition scan would hold the stripe lock for the
        whole partition's width on every increment."""
        import numpy as np
        C = batch.n_lanes - 9
        col_lane = batch.lanes[:, 6 + C]
        cand = np.flatnonzero(
            (col_lane == np.uint32(column))
            & ((batch.flags & FLAG_TOMBSTONE) == 0))
        total, ts = 0, 0
        for i in cand:
            bck, bpath, bval = batch.cell_payload(int(i))
            if bck != ck or bpath != shard_path:
                continue
            if int(batch.ts[i]) >= ts:
                total = int.from_bytes(bval, "big", signed=True)
                ts = int(batch.ts[i])
        return total, ts

    # ------------------------------------------------------- coordinator --

    def mutate(self, keyspace: str, mutation: Mutation, cl: str) -> None:
        """Coordinator side: pick the leader and hand it the deltas.
        The leader acks only after the shard replication reached `cl`."""
        replicas, _strat, _token = self.node.proxy._plan(keyspace,
                                                         mutation.pk)
        live = [r for r in replicas if self.node.is_alive(r)]
        if not live:
            from .coordinator import UnavailableException
            raise UnavailableException(
                "no live replica to lead the counter write")
        if self.node.endpoint in live:
            self.apply_as_leader(keyspace, mutation, cl)
            return
        leader = live[0]
        done = threading.Event()
        box: dict = {}

        def on_rsp(msg):
            box["ok"] = True
            done.set()

        def on_fail(msg):
            # FAILURE_RSP carries {"kind": exc class name, "error": repr};
            # a reap timeout passes the bare message id instead
            box["err"] = getattr(msg, "payload", None)
            done.set()

        # leader waits up to the counter-write timeout for its
        # replication CL, so the origin waits longer than one (the
        # counter_write_request_timeout knob, hot-reloadable through
        # the coordinator's listener; the blanket proxy.timeout setter
        # still covers it for tests)
        budget = self.node.proxy.counter_write_timeout * 2
        self.node.messaging.send_with_callback(
            Verb.COUNTER_REQ, (mutation.serialize(), cl), leader,
            on_response=on_rsp, on_failure=on_fail, timeout=budget)
        from .coordinator import TimeoutException, UnavailableException
        if not done.wait(budget):
            raise TimeoutException(
                f"counter leader {leader.name} did not ack")
        if "ok" not in box:
            err = box.get("err")
            kind = self.node.messaging.failure_kind(err)
            text = err.get("error") if isinstance(err, dict) else err
            if kind == "UnavailableException":
                # surface the leader's CL failure as what it is — the
                # caller must not treat 'not enough replicas' as a
                # maybe-applied timeout
                raise UnavailableException(
                    f"counter leader {leader.name}: {text}")
            raise TimeoutException(
                f"counter leader {leader.name} failed: {text!r}")

    def _handle(self, msg):
        """Leader's COUNTER_REQ handler: punt to the counter stage —
        apply_as_leader blocks on replication acks that can only be
        processed by this dispatch thread."""
        data, cl = msg.payload
        m = Mutation.deserialize(data)
        t = self.node.schema.table_by_id(m.table_id)

        def run():
            try:
                self.apply_as_leader(t.keyspace, m, cl)
                self.node.messaging.respond(msg, Verb.COUNTER_RSP, True)
            except Exception as e:
                self.node.messaging.respond_failure(msg, e)

        self._stage.submit(run)
        return None
