"""CompactionExecutor: concurrent compactor slots + active-task registry.

Reference counterparts: db/compaction/CompactionManager.java:2042
(CompactionExecutor — a JMXEnabledThreadPoolExecutor sized by
`concurrent_compactors`), db/compaction/ActiveCompactions.java (the
registry behind `nodetool compactionstats` and the
system_views.sstable_tasks virtual table) and CompactionInfo.java /
CompactionInfo.Holder (per-task progress: operation type, total/completed
bytes, unit).

Shape here:

  CompactionExecutor   N worker threads pulling from a task queue;
                       N is hot-resizable (nodetool
                       setconcurrentcompactors). `inline=True` (or
                       submit(..., inline=True)) executes on the caller
                       thread — the deterministic path sim/ and tests
                       drive; the worker pool never sees the task.
  ActiveCompactions    begin/finish registry of CompactionProgress
                       handles; snapshot() feeds nodetool
                       compactionstats, the
                       system_views.compactions_in_progress virtual
                       table and service/metrics gauges.
  CompactionProgress   mutable per-task holder the task updates as it
                       runs: phase (decode/merge/compress/io_write),
                       bytes read/written, ETA from the observed rate.

Completion statistics land in service/metrics.GLOBAL
(compaction.tasks_completed, compaction.bytes_read, ...) — the
CompactionMetrics group of the reference.
"""
from __future__ import annotations

import itertools
import threading
import time


class CompactionProgress:
    """Per-task progress holder (CompactionInfo role). The running task
    mutates it; readers take snapshot() — single attribute writes are
    atomic under the GIL, and a torn multi-field read only skews a
    progress row, never correctness."""

    _ids = itertools.count(1)

    def __init__(self, keyspace: str = "", table: str = "",
                 kind: str = "Compaction", total_bytes: int = 0):
        self.op_id = next(self._ids)
        self.keyspace = keyspace
        self.table = table
        self.kind = kind                 # OperationType
        self.total_bytes = total_bytes
        self.bytes_read = 0
        self.bytes_written = 0
        self.phase = "pending"
        self.started_at = time.time()
        self._t0 = time.monotonic()
        # `nodetool stop` lands HERE, per task (CompactionInfo.Holder
        # .stop()): a shared event cleared by one slot would silently
        # cancel a stop another slot's task had not yet polled
        self.stop_requested = False

    def request_stop(self) -> None:
        self.stop_requested = True

    def add_read(self, n: int) -> None:
        self.bytes_read += n

    def add_written(self, n: int) -> None:
        self.bytes_written += n

    def set_phase(self, phase: str) -> None:
        self.phase = phase

    def snapshot(self) -> dict:
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        read = self.bytes_read
        total = self.total_bytes
        rate = read / elapsed
        remaining = max(total - read, 0)
        eta = remaining / rate if rate > 0 and total else None
        return {
            "id": self.op_id,
            "keyspace": self.keyspace,
            "table": self.table,
            "kind": self.kind,
            "phase": self.phase,
            "total_bytes": total,
            "bytes_read": read,
            "bytes_written": self.bytes_written,
            "progress_pct": round(100.0 * read / total, 2) if total else 0.0,
            "active_seconds": round(elapsed, 3),
            "eta_seconds": round(eta, 3) if eta is not None else None,
            "started_at": self.started_at,
        }


class ActiveCompactions:
    """Registry of in-flight CompactionProgress handles
    (ActiveCompactions.java). begin/finish bracket task execution;
    snapshot() is the read surface for nodetool + virtual tables."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: dict[int, CompactionProgress] = {}

    def begin(self, progress: CompactionProgress) -> None:
        with self._lock:
            self._active[progress.op_id] = progress

    def finish(self, progress: CompactionProgress) -> None:
        with self._lock:
            self._active.pop(progress.op_id, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._active)

    def snapshot(self) -> list[dict]:
        with self._lock:
            handles = list(self._active.values())
        return [h.snapshot() for h in handles]

    def stop_all(self) -> int:
        """Request cooperative stop of every in-flight task (`nodetool
        stop`); each aborts at its next between-rounds poll. Returns the
        number of tasks signalled."""
        with self._lock:
            handles = list(self._active.values())
        for h in handles:
            h.request_stop()
        return len(handles)


class CompactionFuture:
    """Result handle for a submitted task (the executor is stdlib-free by
    design: concurrent.futures would drag in its own shutdown semantics
    that fight the hot-resize path)."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def _complete(self, result=None, error: BaseException | None = None):
        self._result = result
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError("compaction task still running")
        if self._error is not None:
            raise self._error
        return self._result


class CompactionExecutor:
    """N concurrent compactor slots over a shared task queue.

    Workers are plain threads (compaction work releases the GIL in its
    hot paths: native merge FFI, compression FFI, O_DIRECT writes), so
    N slots genuinely overlap on multi-core hosts and still interleave
    usefully on one core (CPU work overlaps another task's disk waits).
    """

    def __init__(self, concurrent: int = 1, name: str = "CompactionExecutor"):
        import queue

        self.name = name
        self._queue: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._workers: list[threading.Thread] = []
        self._target = 0
        self._active_count = 0
        self._completed = 0
        self._shutdown = False
        self.set_concurrent(max(int(concurrent), 1))

    # ---------------------------------------------------------- sizing --

    @property
    def concurrent(self) -> int:
        return self._target

    def set_concurrent(self, n: int) -> None:
        """Hot-resize the slot count (nodetool setconcurrentcompactors).
        Growing raises the target (workers spawn lazily on submit, so
        inline-only deployments — tests, sim — never carry idle
        threads); shrinking lowers it and surplus workers exit after
        their CURRENT task (or within one poll tick when idle),
        immediately, not after the queued backlog drains."""
        n = max(int(n), 1)
        with self._lock:
            if self._shutdown:
                raise RuntimeError("executor is shut down")
            self._target = n
            if self._workers:          # pool already live: grow now
                self._spawn_locked()

    def _spawn_locked(self) -> None:
        while len(self._workers) < self._target:
            w = threading.Thread(target=self._work_loop,
                                 name=f"{self.name}-w", daemon=True)
            self._workers.append(w)
            w.start()

    # ---------------------------------------------------------- submit --

    def submit(self, fn, *args, inline: bool = False) -> CompactionFuture:
        """Queue fn(*args) for a compactor slot; returns a future.
        inline=True runs it on the CALLER thread before returning — the
        synchronous mode sim/ determinism and run_pending() rely on (no
        worker-thread scheduling enters the picture)."""
        fut = CompactionFuture()
        if inline:
            self._run_one(fn, args, fut)
            return fut
        # enqueue under the lock: a shutdown() racing this submit must
        # either see the task (and fail its future) or reject it here —
        # never strand an un-completed future on an abandoned queue
        with self._lock:
            if self._shutdown:
                raise RuntimeError("executor is shut down")
            self._queue.put((fn, args, fut))
            self._spawn_locked()
        return fut

    def _run_one(self, fn, args, fut: CompactionFuture) -> None:
        with self._lock:
            self._active_count += 1
        try:
            fut._complete(result=fn(*args))
        except BaseException as e:
            fut._complete(error=e)
        finally:
            with self._lock:
                self._active_count -= 1
                self._completed += 1

    # idle poll period: the latency bound on a shrunk/shut-down worker
    # noticing it should exit while blocked on an empty queue
    POLL_SECONDS = 0.2

    def _work_loop(self) -> None:
        import queue as _queue

        me = threading.current_thread()
        while True:
            with self._lock:
                if self._shutdown or len(self._workers) > self._target:
                    if me in self._workers:
                        self._workers.remove(me)
                    return
            try:
                fn, args, fut = self._queue.get(timeout=self.POLL_SECONDS)
            except _queue.Empty:
                continue
            self._run_one(fn, args, fut)

    # ----------------------------------------------------------- stats --

    def stats(self) -> dict:
        """tpstats row (JMXEnabledThreadPoolExecutor gauges)."""
        with self._lock:
            return {"pool": self.name, "active": self._active_count,
                    "pending": self._queue.qsize(),
                    "completed": self._completed,
                    "concurrent": self._target}

    def shutdown(self, wait: bool = True, timeout: float = 10.0) -> None:
        import queue as _queue

        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            workers = list(self._workers)
            # fail queued-but-never-started tasks: their futures must
            # complete or a result() with no timeout hangs forever
            while True:
                try:
                    _fn, _args, fut = self._queue.get_nowait()
                except _queue.Empty:
                    break
                fut._complete(error=RuntimeError(
                    "executor shut down before task ran"))
        if wait:
            deadline = time.monotonic() + timeout
            for w in workers:
                w.join(timeout=max(deadline - time.monotonic(), 0.0))


def record_completion(stats: dict, seconds: float) -> None:
    """Fold one finished task into the global metrics registry
    (CompactionMetrics: totalCompactionsCompleted, bytesCompacted)."""
    from ..service.metrics import GLOBAL

    GLOBAL.incr("compaction.tasks_completed")
    GLOBAL.incr("compaction.bytes_read", int(stats.get("bytes_read", 0)))
    GLOBAL.incr("compaction.bytes_written",
                int(stats.get("bytes_written", 0)))
    GLOBAL.incr("compaction.cells_written",
                int(stats.get("cells_written", 0)))
    GLOBAL.hist("compaction.task").update_us(seconds * 1e6)
