"""Client-facing native-protocol layer (transport/ in the reference).

`frame` is the v4/v5 wire codec (envelopes, v5 CRC segment framing,
body primitives, result encoding); `server` is the selector-based
event-loop CQL server (transport/Server.java + Dispatcher.java roles);
`admission` is the overload/permit/rate-limit gate in front of the
request executor. `cassandra_tpu.transport_server` remains as a
back-compat shim re-exporting the public surface.
"""
from .server import CQLServer  # noqa: F401
