"""CompactionManager: background compaction scheduling over the
concurrent CompactionExecutor.

Reference counterpart: db/compaction/CompactionManager.java:142
(submitBackground:237, CompactionExecutor:2042, ActiveCompactions, rate
limiting via compaction_throughput). Tasks execute on the executor's N
compactor slots (`concurrent_compactors`); tests and sim/ drive the
executor's synchronous inline mode with run_pending(), so scheduling
stays deterministic there. The shared token-bucket limiter is debited by
each task per merge round (utils/ratelimit.py).

Input claiming: every task executed through the manager first CLAIMS
its input generations in a per-table registry and a task that cannot
claim all inputs is dropped (the store gets re-enqueued by the next
flush notification). The per-store cfs_lock — which serializes
selection+execution per table — is the PRIMARY overlap guard; the claim
registry is the enforced invariant behind it: it catches tasks driven
onto the executor outside the lock, keeps `compactionstats` able to
report what is being rewritten, and is what would make narrowing
cfs_lock to selection-only safe later. The reference's analog is
lifecycle transaction ownership (LifecycleTransaction.obsoletes /
Tracker.tryModify).
"""
from __future__ import annotations

import queue
import threading
from ..utils import lockwitness
import time

from ..utils.ratelimit import RateLimiter  # noqa: F401  (re-exported)
from .executor import (ActiveCompactions, CompactionExecutor,
                       CompactionProgress, record_completion)
from .strategies import get_strategy


class CompactionManager:
    def __init__(self, throughput_mib_s: float = 0.0, auto: bool = False,
                 concurrent: int = 1):
        self.limiter = RateLimiter(throughput_mib_s)
        self.active = ActiveCompactions()
        self.executor = CompactionExecutor(concurrent)
        self.auto = auto
        # nodetool disableautocompaction: queued stores stay queued,
        # nothing new runs until re-enabled
        self.paused = False
        self._queue: queue.Queue = queue.Queue()
        self._pending_cfs: set = set()
        self._lock = lockwitness.make_lock("compaction.manager")
        self._cfs_locks: dict = {}   # table_id -> rewrite mutex
        # mesh-width source for the gauges: the owning engine points
        # this at ITS settings knob (the fanout global is process-wide
        # last-writer-wins state — a co-hosted engine's knob must not
        # leak into this engine's engine-scoped metrics vtable)
        from ..parallel import fanout
        self.mesh_devices_fn = fanout.mesh_devices
        self._compacting: dict = {}  # table_id -> set of claimed gens
        self._stop = threading.Event()
        # programmatic kill switch wired onto every registered store as
        # cfs.compaction_abort: tasks poll it each round and abort (their
        # lifecycle txn rolls back). The SETTER owns clearing it — while
        # set, every new task aborts too. `nodetool stop` does not use
        # it; operator stops land per-task via stop_active()
        self.abort_event = threading.Event()
        self._worker: threading.Thread | None = None
        self.completed: list[dict] = []
        if auto:
            self._worker = threading.Thread(target=self._run_loop,
                                            daemon=True)
            self._worker.start()

    def set_throughput(self, mib_per_s: float) -> None:
        self.limiter.set_rate(mib_per_s)

    def pending_tasks(self) -> int:
        """Submissions not yet running: executor backlog + stores queued
        with the manager (the single source for every pending surface —
        compactionstats, tpstats, the metrics gauge)."""
        return self.executor.stats()["pending"] + self._queue.qsize()

    def gauges(self) -> dict:
        """Live CompactionMetrics gauges (pendingTasks/activeTasks),
        ENGINE-scoped: served through this engine's system_views.metrics
        vtable rather than the process-global registry, so multi-node
        processes (SimCluster, LocalCluster) never cross-report."""
        return {
            "compaction.active_tasks": float(len(self.active)),
            "compaction.pending_tasks": float(self.pending_tasks()),
            "compaction.throughput_mib_per_sec": self.limiter.mib_per_s,
            "compaction.mesh_devices": float(self.mesh_devices_fn()),
        }

    def set_concurrent_compactors(self, n: int) -> None:
        """nodetool setconcurrentcompactors: hot-resize the slot count."""
        self.executor.set_concurrent(n)

    # ----------------------------------------------------------- register --

    def register(self, cfs) -> None:
        """Hook the CFS flush notification (Tracker -> strategy manager
        notification path in the reference)."""
        cfs.compaction_listener = self.submit_background
        cfs.compaction_abort = self.abort_event

    def enable_auto(self) -> None:
        """Start the background worker (daemon deployments; tests keep
        auto off and drain with run_pending())."""
        if self.auto:
            return
        self.auto = True
        self._worker = threading.Thread(target=self._run_loop,
                                        daemon=True)
        self._worker.start()

    def submit_background(self, cfs) -> None:
        with self._lock:
            if cfs in self._pending_cfs:
                return
            self._pending_cfs.add(cfs)
        self._queue.put(cfs)
        if not self.auto:
            return  # tests call run_pending() explicitly

    # ------------------------------------------------------------- claims --

    def _claim(self, cfs, readers) -> bool:
        """Atomically claim the input generations; False if ANY is
        already owned by an in-flight task (overlap = stale selection)."""
        gens = {r.desc.generation for r in readers}
        with self._lock:
            claimed = self._compacting.setdefault(cfs.table.id, set())
            if gens & claimed:
                return False
            claimed |= gens
        return True

    def _release(self, cfs, readers) -> None:
        with self._lock:
            claimed = self._compacting.get(cfs.table.id)
            if claimed is not None:
                claimed -= {r.desc.generation for r in readers}

    def compacting_generations(self, cfs) -> set:
        with self._lock:
            return set(self._compacting.get(cfs.table.id, set()))

    # ------------------------------------------------------------ execute --

    def run_pending(self, max_tasks: int = 100) -> int:
        """Drain the queue synchronously (executor inline mode: tasks run
        on THIS thread, deterministically); returns tasks executed."""
        done = 0
        while done < max_tasks:
            try:
                cfs = self._queue.get_nowait()
            except queue.Empty:
                break
            with self._lock:
                self._pending_cfs.discard(cfs)
            done += self.executor.submit(self._maybe_compact, cfs,
                                         inline=True).result()
        return done

    MAX_TASKS_PER_SUBMISSION = 4  # bounds livelock if a strategy re-selects

    def cfs_lock(self, cfs) -> threading.Lock:
        """Per-store mutex serializing sstable-set rewrites: background
        compaction vs cleanup/scrub/anticompaction. Without it, a
        compaction selected before a maintenance rewrite could merge
        the REPLACED original back into the live set, resurrecting the
        cells the maintenance op dropped. Task SELECTION and execution
        must both happen under it."""
        with self._lock:
            return self._cfs_locks.setdefault(cfs.table.id,
                                              lockwitness.make_lock("compaction.cfs_rewrite"))

    def _execute_task(self, cfs, task, kind: str = "Compaction"):
        """Claim inputs, run one task with progress + throttle + metrics
        plumbing, release. Returns the stats dict, or None when the
        selection lost the claim race (caller may reselect)."""
        if not self._claim(cfs, task.inputs):
            return None
        info = CompactionProgress(
            keyspace=cfs.table.keyspace, table=cfs.table.name, kind=kind,
            total_bytes=sum(r.data_size for r in task.inputs))
        task.limiter = self.limiter
        task.progress = info
        self.active.begin(info)
        from ..service import diagnostics
        diagnostics.publish("compaction.start",
                            keyspace=cfs.table.keyspace,
                            table=cfs.table.name, kind=kind,
                            inputs=len(task.inputs),
                            bytes=info.total_bytes)
        t0 = time.monotonic()
        stats = None
        try:
            stats = task.execute()
        except BaseException as e:
            diagnostics.publish("compaction.abort",
                                keyspace=cfs.table.keyspace,
                                table=cfs.table.name, kind=kind,
                                error=repr(e))
            raise
        finally:
            self.active.finish(info)
            self._release(cfs, task.inputs)
        record_completion(stats, time.monotonic() - t0)
        self.completed.append(stats)
        diagnostics.publish("compaction.finish",
                            keyspace=cfs.table.keyspace,
                            table=cfs.table.name, kind=kind,
                            bytes_read=stats.get("bytes_read", 0),
                            bytes_written=stats.get("bytes_written", 0),
                            seconds=round(stats.get("seconds", 0.0), 3))
        return stats

    def _maybe_compact(self, cfs, locked: bool = False) -> int:
        from ..storage.sstable.reader import CorruptSSTableError
        n = 0
        lock = self.cfs_lock(cfs)
        if not locked:
            lock.acquire()
        try:
            strategy = get_strategy(cfs)
            while n < self.MAX_TASKS_PER_SUBMISSION:
                task = strategy.next_background_task()
                if task is None:
                    break
                try:
                    stats = self._execute_task(cfs, task)
                except CorruptSSTableError:
                    # the task aborted itself (txn rolled back) and —
                    # under best_effort — quarantined the rotten input.
                    # If the input left the live set, re-select: the
                    # strategy re-plans without it. If it is still
                    # live (policy ignore/stop/die), stop: re-selecting
                    # would pick the same doomed inputs forever.
                    live = {s.desc.generation for s in cfs.live_sstables()}
                    if all(r.desc.generation in live for r in task.inputs):
                        break
                    strategy = get_strategy(cfs)
                    continue
                if stats is None:
                    break   # input claimed elsewhere: drop this
                    #         selection (a later flush re-enqueues)
                n += 1
        finally:
            if not locked:
                lock.release()
        return n

    def stop_active(self) -> int:
        """`nodetool stop`: request cooperative abort of every in-flight
        task, each through ITS OWN progress handle — no shared-event
        clear can cancel a stop another slot has not polled yet."""
        return self.active.stop_all()

    def major_compaction(self, cfs) -> dict | None:
        """nodetool compact equivalent (synchronous). A prior `nodetool
        stop` never carries over: stop requests land on the in-flight
        tasks' own progress handles, and this task gets a fresh one."""
        with self.cfs_lock(cfs):
            task = get_strategy(cfs).major_task()
            if task is None:
                return None
            return self._execute_task(cfs, task, kind="Major")

    def major_compaction_async(self, cfs):
        """Submit a major compaction to a compactor slot; returns a
        CompactionFuture. While it runs, active.snapshot() / nodetool
        compactionstats report its live progress."""
        return self.executor.submit(self.major_compaction, cfs)

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            if self.paused:
                self._stop.wait(0.2)
                continue
            try:
                cfs = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            with self._lock:
                self._pending_cfs.discard(cfs)
            try:
                # hand the store to a compactor slot: up to N stores
                # compact concurrently (same-store tasks still serialize
                # on cfs_lock). The shared abort_event is NOT cleared
                # here — that would cancel a `nodetool stop` another
                # slot's task has not polled yet; executor-era stops go
                # through per-task progress handles (stop_active)
                self.executor.submit(self._compact_bg, cfs)
            except Exception:   # background task failure must not kill loop
                import traceback
                traceback.print_exc()

    RETRY_DELAY = 0.25   # backoff when a store's lock is held elsewhere

    def _compact_bg(self, cfs) -> int:
        """Background-slot entry: try-acquire the store lock so a slot
        never PARKS behind another slot's long compaction of the same
        store (that would starve other tables of a worker); on
        contention, requeue the store after a short delay."""
        lock = self.cfs_lock(cfs)
        if not lock.acquire(blocking=False):
            t = threading.Timer(self.RETRY_DELAY,
                                lambda: self.submit_background(cfs))
            t.daemon = True
            t.start()
            return 0
        try:
            return self._maybe_compact(cfs, locked=True)
        except Exception:
            import traceback
            traceback.print_exc()
            return 0
        finally:
            lock.release()

    def close(self) -> None:
        self._stop.set()
        if self._worker:
            self._worker.join(timeout=5)
        self.executor.shutdown(wait=True, timeout=5)
