"""Timestamps and expiry semantics.

Reference semantics: write timestamps are microseconds since epoch
(cql3 'USING TIMESTAMP'); localDeletionTime is seconds since epoch
(db/DeletionTime.java, db/LivenessInfo.java); NO_TTL=0, NO_EXPIRY handled
via sentinel (db/LivenessInfo.java:36-50)."""
from __future__ import annotations

import threading
import time

NO_TIMESTAMP = -(1 << 63)          # LivenessInfo.NO_TIMESTAMP
NO_TTL = 0
NO_DELETION_TIME = 0x7FFFFFFF      # int max: "not deleted / never expires"
LIVE_DELETION = (NO_TIMESTAMP, NO_DELETION_TIME)
# largest TTL CQL accepts: 20 years (cql3/Attributes.java MAX_TTL)
MAX_TTL = 20 * 365 * 24 * 3600

# ctpulint: clock-injectable
# patchable wall clock (seconds, float). Tests install a virtual clock
# here to make TTL expiry deterministic; production leaves time.time.
CLOCK = time.time


def expiration_time(now_s: int, ttl: int) -> int:
    """localDeletionTime of an expiring cell, CAPPED at the int32
    horizon instead of overflowing (the 2038 problem —
    db/ExpirationDateOverflowHandling.java policy CAP: a write whose
    expiry exceeds the representable maximum lives until the cap, it
    does not wrap into the past and vanish)."""
    return min(now_s + ttl, NO_DELETION_TIME - 1)

_last_micros = 0
_micros_lock = threading.Lock()


def now_micros() -> int:
    """Monotonic-per-process microsecond clock (ClientState.getTimestamp
    semantics: never returns the same value twice, even across threads)."""
    global _last_micros
    with _micros_lock:
        # ctpulint: allow(clock-discipline, reason=write timestamps must stay unique and monotonic PROCESS-wide; the sim patches CLOCK (now_seconds/TTL expiry) only — pinning micros to a virtual clock would hand equal timestamps to every write in a tick and break last-write-wins)
        t = time.time_ns() // 1000
        if t <= _last_micros:
            t = _last_micros + 1
        _last_micros = t
        return t


def now_seconds() -> int:
    return int(CLOCK())
