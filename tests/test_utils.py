"""Substrate tests: murmur3 (against known Cassandra token vectors),
varint round-trips, byte-comparable order properties, bloom filter."""
import random
import struct

import numpy as np
import pytest

from cassandra_tpu.utils import bloom, bytecomp, murmur3, varint


def test_murmur3_reference_vectors():
    # Cross-check scalar impl against the canonical smhasher vectors
    # (all-ASCII keys, where Cassandra's sign-extended tail == canonical):
    # murmur3 x64_128("hello", seed=0) h1 is well known.
    h1, h2 = murmur3.hash128(b"hello")
    assert (h1, h2) == (0xCBD8A7B341BD9B02, 0x5B1E906A48AE1D19)
    h1, h2 = murmur3.hash128(b"hello, world")
    assert (h1, h2) == (0x342FAC623A5EBC8E, 0x4CDCBC079642414D)
    h1, h2 = murmur3.hash128(b"The quick brown fox jumps over the lazy dog.")
    assert (h1, h2) == (0xCD99481F9EE902C9, 0x695DA1A38987B6E7)


def _java_tail_oracle(data: bytes) -> tuple[int, int]:
    """Independent slow model of the Java-signed-byte murmur3 variant used
    by Murmur3Partitioner (murmur3 x64/128 is public domain; the quirk is
    sign-extended tail bytes, MurmurHash.java:216-232)."""
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def fmix(k):
        k ^= k >> 33
        k = k * 0xFF51AFD7ED558CCD & M
        k ^= k >> 33
        k = k * 0xC4CEB9FE1A85EC53 & M
        k ^= k >> 33
        return k

    h1 = h2 = 0
    c1, c2 = 0x87C37B91114253D5, 0x4CF5AD432745937F
    nb = len(data) // 16
    for i in range(nb):
        k1 = int.from_bytes(data[i * 16: i * 16 + 8], "little")
        k2 = int.from_bytes(data[i * 16 + 8: i * 16 + 16], "little")
        k1 = rotl(k1 * c1 & M, 31) * c2 & M
        h1 = ((rotl(h1 ^ k1, 27) + h2) * 5 + 0x52DCE729) & M
        k2 = rotl(k2 * c2 & M, 33) * c1 & M
        h2 = ((rotl(h2 ^ k2, 31) + h1) * 5 + 0x38495AB5) & M
    tail = data[nb * 16:]
    signed = [b - 256 if b >= 128 else b for b in tail]
    k1 = k2 = 0
    if len(tail) >= 9:
        for i in range(8, len(tail)):
            k2 ^= (signed[i] << (8 * (i - 8))) & M
        h2 ^= rotl(k2 * c2 & M, 33) * c1 & M
    if tail:
        for i in range(min(8, len(tail))):
            k1 ^= (signed[i] << (8 * i)) & M
        h1 ^= rotl(k1 * c1 & M, 31) * c2 & M
    h1 ^= len(data)
    h2 ^= len(data)
    h1 = (h1 + h2) & M
    h2 = (h2 + h1) & M
    h1 = fmix(h1)
    h2 = fmix(h2)
    h1 = (h1 + h2) & M
    h2 = (h2 + h1) & M
    return h1, h2


def test_murmur3_java_signed_tail():
    rng = random.Random(11)
    keys = [b"\x80", b"\xff" * 15, b"\x80" * 9, bytes(range(200, 216)) + b"\xfe\x80"]
    keys += [bytes(rng.randrange(128, 256) for _ in range(n)) for n in range(1, 40)]
    for k in keys:
        assert murmur3.hash128(k) == _java_tail_oracle(k), k


def test_murmur3_batch_matches_scalar():
    rng = random.Random(42)
    keys = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 70)))
            for _ in range(300)]
    h1b, h2b = murmur3.hash128_batch(keys)
    for i, k in enumerate(keys):
        h1, h2 = murmur3.hash128(k)
        assert (int(h1b[i]), int(h2b[i])) == (h1, h2), f"key {i} len {len(k)}"


def test_tokens_batch():
    keys = [str(i).encode() for i in range(100)]
    toks = murmur3.tokens_of(keys)
    for i, k in enumerate(keys):
        assert int(toks[i]) == murmur3.token_of(k)


def test_varint_roundtrip():
    vals = [0, 1, 127, 128, 255, 256, 2**14, 2**21 - 1, 2**35, 2**56 + 17,
            2**63 - 1, 2**64 - 1]
    for v in vals:
        out = bytearray()
        varint.write_unsigned_vint(v, out)
        got, pos = varint.read_unsigned_vint(out, 0)
        assert got == v and pos == len(out), v
    for v in [0, -1, 1, -2**31, 2**31, -2**62, 2**62]:
        out = bytearray()
        varint.write_signed_vint(v, out)
        got, pos = varint.read_signed_vint(out, 0)
        assert got == v and pos == len(out), v


def test_varint_ordering_of_length():
    # single byte for < 128
    out = bytearray(); varint.write_unsigned_vint(127, out)
    assert len(out) == 1
    out = bytearray(); varint.write_unsigned_vint(128, out)
    assert len(out) == 2


def _sorted_check(pairs):
    """pairs: list of (value, encoding); assert encoding order == value order."""
    by_val = sorted(pairs, key=lambda p: p[0])
    by_enc = sorted(pairs, key=lambda p: p[1])
    assert [p[0] for p in by_val] == [p[0] for p in by_enc]


def test_bytecomp_int_order():
    rng = random.Random(7)
    vals = [rng.randrange(-2**63, 2**63) for _ in range(200)] + [0, 1, -1, 2**63 - 1, -2**63]
    _sorted_check([(v, bytecomp.encode_int(v, 8)) for v in vals])
    for v in vals:
        assert bytecomp.decode_int(bytecomp.encode_int(v, 8), 8) == v


def test_bytecomp_float_order():
    rng = random.Random(9)
    vals = [rng.uniform(-1e10, 1e10) for _ in range(200)] + [0.0, -0.0, 1.5, -1.5, 1e-300, -1e-300, float("inf"), float("-inf")]
    uniq = sorted(set(vals))
    _sorted_check([(v, bytecomp.encode_float(v)) for v in uniq])
    for v in uniq:
        assert bytecomp.decode_float(bytecomp.encode_float(v)) == v


def test_bytecomp_varint_order():
    vals = [0, 1, -1, 255, -255, 2**100, -2**100, 12345678901234567890,
            -12345678901234567890, 7, -7]
    _sorted_check([(v, bytecomp.encode_varint(v)) for v in vals])
    for v in vals:
        assert bytecomp.decode_varint(bytecomp.encode_varint(v)) == v


def test_composite_order_asc():
    rng = random.Random(3)
    tuples = []
    for _ in range(300):
        t = (bytes(rng.randrange(256) for _ in range(rng.randrange(0, 6))),
             bytes(rng.randrange(256) for _ in range(rng.randrange(0, 6))))
        tuples.append(t)
    tuples = sorted(set(tuples))
    _sorted_check([(t, bytecomp.encode_composite(list(t))) for t in tuples])
    for t in tuples:
        assert tuple(bytecomp.decode_composite(
            bytecomp.encode_composite(list(t)), 2)) == t


def test_composite_order_desc():
    vals = sorted({bytes([b]) * n for b in (0, 1, 127, 255) for n in (0, 1, 2, 3)})
    pairs = [((v,), bytecomp.encode_composite([v], [True])) for v in vals]
    # descending: encoding order must be REVERSE of value order
    by_val = sorted(pairs, key=lambda p: p[0], reverse=True)
    by_enc = sorted(pairs, key=lambda p: p[1])
    assert [p[0] for p in by_val] == [p[0] for p in by_enc]
    for v in vals:
        assert bytecomp.decode_composite(
            bytecomp.encode_composite([v], [True]), 1, [True]) == [v]


def test_composite_mixed_asc_desc():
    items = [(a, b) for a in (b"a", b"b") for b in (b"x", b"y", b"z")]
    enc = {t: bytecomp.encode_composite(list(t), [False, True]) for t in items}
    order = sorted(items, key=lambda t: enc[t])
    # expect a ASC then b DESC
    expected = sorted(items, key=lambda t: (t[0], [255 - c for c in t[1]]))
    assert order == expected


def test_bloom_filter():
    bf = bloom.BloomFilter.create(1000, 0.01)
    keys = [f"key-{i}".encode() for i in range(1000)]
    bf.add_batch(keys)
    assert bf.might_contain_batch(keys).all()
    other = [f"other-{i}".encode() for i in range(2000)]
    fp = int(np.sum(bf.might_contain_batch(other)))
    assert fp < 100  # ~1% target
    data = bf.serialize()
    bf2 = bloom.BloomFilter.deserialize(data)
    assert bf2.might_contain_batch(keys).all()
