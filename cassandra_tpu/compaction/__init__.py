from .manager import CompactionManager  # noqa: F401
from .strategies import get_strategy  # noqa: F401
