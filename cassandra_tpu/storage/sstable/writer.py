"""SSTable writer: sorted CellBatches -> ctpu components.

Reference counterpart: io/sstable/format/SortedTableWriter.java:76 (append
loop), io/compress/CompressedSequentialWriter.java:43 (chunk+CRC write
path), BigTableWriter.java:237-254 (bloom + index build during append).

The writer consumes *sorted* batches (flush output or merge-kernel output),
cuts fixed-size segments, compresses each segment's three blocks through
the table codec's batch API (one FFI crossing per segment), and maintains
the bloom filter / partition directory / stats as it goes.

Write-leg staging (docs/compaction-executor.md):

  serial        compress + write on the caller thread.
  threaded_io   compressed segments stage through a bounded queue to a
                dedicated I/O thread (compress k+1 overlaps write k).
  parallel      compress_pool= set: segments compress CONCURRENTLY on a
                shared worker pool (ops/codec.py native calls release
                the GIL) and re-sequence through the ordered completion
                queue drained by the I/O thread — file bytes identical
                to the serial path for ANY pool size (the adaptive-skip
                decisions run on a fixed SKIP_DECISION_LAG outcome
                stream, see _decide_attempt). Requires the fused native
                packer; encrypted tables / codecs without a native id
                silently keep the serial per-block chain.
"""
from __future__ import annotations

import json
import mmap
import os
import queue
import struct
import threading
import time
import zlib

import numpy as np

from ...ops.codec import CompressionParams, SegmentPacker, lanes_shuffle
from ...schema import TableMetadata
from ...utils import bloom, faultfs
from ..cellbatch import CellBatch
from .format import SEGMENT_CELLS, Component, Descriptor


# test seam: per-segment delay hook run by pool workers before packing
# (tests/test_parallel_compress.py forces adversarial completion order
# to prove the ordered queue re-sequences); None in production.
_TEST_SEGMENT_DELAY = None

# sentinel on the outcome stream: the completion stage died — wake a
# producer parked in _decide_attempt so it surfaces the error
_ACCT_FAILED = object()


class _PackJob:
    """One segment's compress work in flight between the producer, a
    CompressorPool worker and the writer's ordered completion (I/O)
    thread. The worker fills total/sizes/crcs (or error) and sets
    ready; the completion thread consumes jobs in submit order."""

    __slots__ = ("seq", "blocks", "attempt", "buf", "n", "raw_lens",
                 "lane_head", "lane_tail", "total", "sizes", "crcs",
                 "compress_s", "error", "ready", "trace")

    def __init__(self, seq: int, blocks: list, attempt: list[bool],
                 buf: "np.ndarray", n: int, lane_head: bytes,
                 lane_tail: bytes):
        self.seq = seq
        self.blocks = blocks
        self.attempt = attempt
        self.buf = buf
        self.n = n
        self.raw_lens = [b.nbytes for b in blocks]
        self.lane_head = lane_head
        self.lane_tail = lane_tail
        self.total = 0
        self.sizes = None
        self.crcs = None
        self.compress_s = 0.0
        self.error: BaseException | None = None
        self.ready = threading.Event()
        self.trace = None   # active TraceState at submit, if any


def build_meta_block(ts: "np.ndarray", ldt: "np.ndarray",
                     ttl: "np.ndarray", flags: "np.ndarray",
                     frame_len: "np.ndarray", val_rel: "np.ndarray"
                     ) -> "np.ndarray":
    """The "ce" META block: ts-delta 8 + ldt 4 + ttl 4 + flags 1 +
    frame_len u32 + val_rel u32 = 25 B/cell. The ts lane is stored as
    per-segment wraparound deltas (first cell absolute; format.py "ce")
    — mod-2^64 arithmetic, so the reader's cumsum rebuild is exact for
    any i64 timestamps. ONE definition of the layout: the host write
    path serializes through here and the device fused-serialize kernel
    (ops/device_write.py) is pinned byte-identical to it by test."""
    n = len(ts)
    tsd = np.empty(n, dtype=np.int64)
    if n:
        tsd[0] = ts[0]
        np.subtract(ts[1:], ts[:-1], out=tsd[1:])
    meta = np.empty(n * 25, dtype=np.uint8)
    pos = 0
    for arr, width in ((tsd, 8),
                       (ldt.astype("<i4", copy=False), 4),
                       (ttl.astype("<i4", copy=False), 4),
                       (flags.astype("u1", copy=False), 1),
                       (frame_len, 4), (val_rel, 4)):
        end = pos + n * width
        meta[pos:end] = np.ascontiguousarray(arr).view(np.uint8)
        pos = end
    return meta


def _part_starts(lanes_c: "np.ndarray", n: int) -> "np.ndarray":
    """Row indices where the partition (first 4 lanes) changes — native
    single pass with a numpy fallback."""
    try:
        from ...ops.native import build as native_build
        lib = native_build.load()
        out = np.empty(n, dtype=np.int64)
        import ctypes
        cnt = lib.part_boundaries(
            lanes_c.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            n, lanes_c.shape[1],
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out[:cnt]
    except Exception:
        part_new = np.ones(n, dtype=bool)
        part_new[1:] = (lanes_c[1:, :4] != lanes_c[:-1, :4]).any(axis=1)
        return np.flatnonzero(part_new)


class SSTableWriter:
    # trickle fsync (conf trickle_fsync role), used by the BUFFERED
    # fallback path only: push dirty pages to disk WHILE later segments
    # compress/serialize, so the commit-time fsync only pays for the tail.
    TRICKLE_FSYNC_BYTES = 16 << 20
    # block preallocation ahead of the write cursor: avoids the
    # delayed-allocation path (and fragmentation) on every extend.
    PREALLOC_BYTES = 32 << 20
    # Data.db is written O_DIRECT through an aligned bounce buffer.
    # Rationale (measured on this box): buffered writes interleaved with
    # compression CPU work collapse to ~60-90 MiB/s under kernel dirty-
    # page throttling (state-dependent, not controllable from userspace),
    # while O_DIRECT runs at ~700 MiB/s steady and leaves the final fsync
    # nearly free because data blocks are already on disk. It also keeps
    # compaction output from evicting the read-path page cache — the
    # reference wants the same and uses posix_fadvise/direct IO options
    # (io/util/SequentialWriterOption, conf commitlog_disk_access_mode).
    DIRECT_ALIGN = 4096
    BOUNCE_BYTES = 8 << 20

    # bounded staging queue for the threaded-I/O mode: compression of
    # segment k+1 overlaps the disk write of segment k; 4 buffers bound
    # the memory held and give backpressure when the disk falls behind
    IO_QUEUE_DEPTH = 4
    # parallel-compress mode: up to this many segments in flight through
    # the pool + ordered completion queue (each holds one pack buffer —
    # the memory bound — and gives the pool its concurrency headroom)
    PARALLEL_QUEUE_DEPTH = 8
    # the adaptive-compression-skip machine decides segment k's attempt
    # flags from the outcomes of segments <= k - LAG (both serial and
    # parallel paths): a FIXED lag makes the decision sequence — and so
    # every stored byte — identical for any compressor pool size, while
    # letting the pool keep LAG segments in flight without stalling.
    SKIP_DECISION_LAG = 8

    def __init__(self, descriptor: Descriptor, table: TableMetadata,
                 estimated_partitions: int = 1024,
                 segment_cells: int = SEGMENT_CELLS,
                 prof: dict | None = None,
                 threaded_io: bool = False,
                 compress_pool=None,
                 metrics_group: str | None = None,
                 device_compress=False):
        """prof: optional dict accumulating per-phase wall seconds
        ('compress' = compress+CRC — plus serialization when no pool;
        'serialize' = block prep when a pool carries the compress leg;
        'io_write' = fd writes).
        threaded_io: stage compressed segments through a bounded queue
        drained by a dedicated I/O thread, so compression of the next
        segment overlaps the previous segment's disk write (the write
        stage of the compaction pipeline; see compaction/executor.py).
        compress_pool: a compress_pool.CompressorPool — segments
        compress concurrently on its workers and re-sequence through
        the ordered completion queue (implies threaded_io). Output is
        byte-identical to the serial path for any worker count. Falls
        back to the serial chain when the fused native packer is
        unavailable (encrypted tables, codecs without a native id).
        metrics_group: service/metrics group prefix ('compaction',
        'flush') for the compress-stage queue-depth/stall metrics.
        device_compress: bool or zero-arg callable — whether the
        device-resident write lane (ops/device_write.py) should hand
        this writer segments it already compressed on-device. A
        callable is re-read PER SEGMENT, so a mid-compaction
        `compaction_device_compress` knob flip takes effect at the
        next segment boundary; output bytes are identical either way
        (the device runs the same deterministic policy encoder as the
        native packer)."""
        self.desc = descriptor
        self.table = table
        self.prof = prof
        self.params: CompressionParams = table.params.compression
        self.compressor = self.params.compressor_or_noop()
        self.segment_cells = segment_cells
        self.K = None  # lanes, learned from first batch
        # fused native write path (ops/native/codec.cpp segment_pack):
        # one GIL-released call per segment does delta+compress+CRC+copy.
        # Encrypted tables keep the per-block Python chain (the AES-CTR
        # keystream lives in storage/encryption.py).
        self._packer = None if getattr(table.params, "encryption", False) \
            else SegmentPacker.create(self.compressor)
        self._pack_out: np.ndarray | None = None
        self._cpool = compress_pool if self._packer is not None else None
        if self._cpool is not None:
            threaded_io = True
        # device-side block compression gate (bool or callable; the
        # lane consults it through _device_compress_now per segment)
        self.device_compress = device_compress if self._packer is not None \
            else False
        self._threaded_io = threaded_io
        self._io_thread: threading.Thread | None = None
        self._io_error: list[BaseException] = []
        self._wq = None
        self._metrics = None
        self._ledger = None
        if metrics_group:
            from ...service.metrics import GLOBAL as _METRICS
            self._metrics = _METRICS.group(metrics_group)
            # unified pipeline ledger (utils/pipeline_ledger.py): the
            # write leg's stages accumulate process-wide under the
            # pipeline named after the metrics group — serialize /
            # compress / io_write busy seconds, producer stalls and the
            # staging-queue high-water all land there
            from ...utils import pipeline_ledger
            led = pipeline_ledger.ledger(metrics_group)
            self._ledger = {
                "serialize": led.stage("serialize"),
                "compress": led.stage("compress"),
                "io_write": led.stage("io_write"),
            }
        if threaded_io:
            # pack-buffer pool: the compress stage packs segment k+1
            # into a free buffer while the I/O thread drains segment k
            # — ZERO copies between stages (ownership travels through
            # the queue and returns here). 2 buffers double-buffer the
            # serial compress thread; parallel mode carries one per
            # in-flight segment plus the one being written.
            depth = self.PARALLEL_QUEUE_DEPTH if self._cpool is not None \
                else self.IO_QUEUE_DEPTH
            self._wq = queue.Queue(maxsize=depth)
            self._pack_free: queue.Queue = queue.Queue()
            n_bufs = depth + 1 if self._cpool is not None else 2
            for _ in range(n_bufs):
                self._pack_free.put(np.empty(0, dtype=np.uint8))

        os.makedirs(descriptor.directory, exist_ok=True)
        data_path = descriptor.tmp_path(Component.DATA)
        self._data_path = data_path   # flush.write fault checkpoint id
        self._direct = True
        try:
            self._data_fd = os.open(
                data_path,
                os.O_WRONLY | os.O_CREAT | os.O_TRUNC | os.O_DIRECT, 0o644)
        except OSError:       # fs without O_DIRECT: buffered + trickle
            self._direct = False
            self._data_fd = os.open(
                data_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        # unbuffered FileIO over the fd: segment blocks are MB-sized
        # memoryviews already — BufferedWriter would only add a copy
        self._data = open(self._data_fd, "wb", buffering=0, closefd=True)
        if self._direct:
            # page-aligned bounce buffer (mmap is always page-aligned);
            # O_DIRECT requires aligned address, offset and length
            self._bounce = mmap.mmap(-1, self.BOUNCE_BYTES)
            self._bounce_mv = memoryview(self._bounce)
            self._bounce_fill = 0
        self._data_crc = 0
        self._data_off = 0
        self._written_off = 0   # bytes actually handed to the fd (the
        #                         I/O thread's cursor in threaded mode)
        self._allocated = 0
        self._index_entries: list[bytes] = []
        self._bloom = bloom.BloomFilter.create(max(estimated_partitions, 16))
        # partition directory accumulators
        self._part_lane4: list[bytes] = []
        self._part_first_cell: list[int] = []
        self._part_pk: list[bytes] = []
        self._last_lane4: bytes | None = None
        # adaptive compression skip, per block stream (meta/lanes/payload):
        # after 4 consecutive raw-stored blocks the next 15 skip the
        # compression attempt entirely, then one probe re-checks. Random
        # blob values (the stress default) store ~every payload block raw,
        # so attempting LZ4 on them was pure CPU waste; compressible
        # streams never enter skip mode. Chunk-granular analog of lz4's
        # own acceleration heuristic. Decisions consume the outcome
        # stream with a fixed SKIP_DECISION_LAG (see _decide_attempt).
        self._raw_streak = [0, 0, 0]
        self._skip_left = [0, 0, 0]
        self._acct_outcomes: queue.SimpleQueue = queue.SimpleQueue()
        self._seq_submitted = 0   # segments whose attempt flags are decided
        self._seq_applied = 0     # outcomes folded into the skip machine
        # monotonic published copy of _data_off: safe to read from any
        # thread (compaction's output-roll check) regardless of which
        # thread owns the real cursor in the current mode
        self._published_off = 0
        self._ck_fits = True   # AND over appended batches' ck_fits_prefix
        # TDE: encrypted tables XOR the on-disk stream with an AES-CTR
        # keystream at its file offset; CRCs/digest cover the CIPHERTEXT
        # so integrity checks don't need keys (storage/encryption.py)
        self._enc = None
        if getattr(table.params, "encryption", False):
            from .. import encryption as enc_mod
            ctx = enc_mod.get_context()
            if ctx is None:
                raise enc_mod.EncryptionError(
                    f"table {table.keyspace}.{table.name} requires "
                    f"encryption but no EncryptionContext is installed")
            self._enc = (ctx, ctx.current_key_id,
                         {c: ctx.new_nonce()
                          for c in (Component.DATA, Component.INDEX,
                                    Component.PARTITIONS)})
        # pending cells not yet cut into a segment
        self._pending: list[CellBatch] = []
        self._pending_cells = 0
        self._total_cells = 0
        # flush/compaction-time zone maps (index/sstable_index.py ZMP1):
        # _emit_segment accumulates per-segment per-column min/max scan
        # keys + live/dead counts on the appending thread (covers the
        # serial, pooled and device-packed legs alike); finish() writes
        # the component. Encrypted tables skip it — plaintext bounds
        # would leak TDE data.
        self._zone_cols = None   # resolved lazily from the table schema
        self._zone_acc: list | None = [] if self._enc is None else None
        self._stats = {
            "min_ts": None, "max_ts": None, "min_ldt": None, "max_ldt": None,
            "tombstones": 0,
        }
        self.level = 0   # LCS level (recorded in Statistics.db)
        # repairedAt epoch millis; 0 = unrepaired (reference
        # StatsMetadata.repairedAt — the repaired/unrepaired compaction
        # split and incremental repair key off this)
        self.repaired_at = 0
        self._finished = False
        self._sync_req = threading.Event()
        self._sync_stop = False
        self._sync_error: OSError | None = None
        self._bytes_since_sync = 0
        # started lazily on the first threshold crossing: small writers
        # (memtable flushes, mesh shards) never pay thread create/join,
        # and an abandoned writer (caller crashed before finish/abort)
        # leaks nothing
        self._syncer: threading.Thread | None = None

    # ---------------------------------------------------------------- api --

    def append(self, batch: CellBatch) -> None:
        """Append a sorted batch; cells must follow all previously appended
        cells in identity-lane order (enforced cheaply at segment cut)."""
        if len(batch) == 0:
            return
        if self.K is None:
            self.K = batch.n_lanes
        assert batch.n_lanes == self.K
        self._ck_fits = self._ck_fits and batch.ck_fits_prefix
        self._pending.append(batch)
        self._pending_cells += len(batch)
        while self._pending_cells >= self.segment_cells:
            self._cut_segment(self.segment_cells)

    def data_offset(self) -> int:
        """Data.db bytes committed by the write pipeline so far — the
        cross-thread-safe progress/roll-check surface (compaction's
        output-size cut-over reads this from its merge-feed thread
        while another thread advances the file). Monotonic; in
        parallel-compress mode it trails appends by the in-flight
        segments, so size-based rolls land a bounded overshoot late."""
        return self._published_off

    def finish(self) -> dict:
        """Flush remaining cells, write all components, atomically rename.
        Returns the stats dict."""
        assert not self._finished
        while self._pending_cells > 0:
            self._cut_segment(min(self.segment_cells, self._pending_cells))
        if self.K is None:
            self.K = 13
        self._stop_io_thread()   # drain staged segments, surface errors
        self._stop_syncer()   # join BEFORE the final fsync + close
        if self._sync_error is not None:
            raise self._sync_error
        if self._direct:
            self._flush_bounce(final=True)
        self._data.flush()
        # drop alignment padding / unused preallocation before the
        # commit-point rename
        os.ftruncate(self._data.fileno(), self._data_off)
        os.fsync(self._data.fileno())
        self._data.close()
        if self._direct:
            self._bounce_mv.release()
            self._bounce.close()

        self._write_index()
        self._write_partitions()
        self._write_filter()
        stats = self._write_stats()
        self._write_digest()
        self._write_zonemap()
        comps = list(Component.ALL)
        if self._enc is not None:
            _ctx, kid, nonces = self._enc
            with open(self.desc.tmp_path(Component.ENCRYPTION), "w") as f:
                json.dump({"key_id": kid,
                           "nonces": {c: n.hex()
                                      for c, n in nonces.items()}}, f)
                f.flush()
                os.fsync(f.fileno())
            comps.insert(-1, Component.ENCRYPTION)
        # TOC last, then atomic renames (TOC rename LAST = commit point).
        # Every component is fsynced before its rename and the directory
        # is fsynced after the TOC rename — otherwise a crash can persist
        # the commit point over truncated/unrenamed components.
        with open(self.desc.tmp_path(Component.TOC), "w") as f:
            f.write("\n".join(comps) + "\n")
            f.flush()
            os.fsync(f.fileno())
        # fsync the components CONCURRENTLY (os.fsync releases the GIL, so
        # the per-file device-flush latencies overlap in the disk queue —
        # serially they cost ~20ms each). Data.db was already fsynced
        # above; TOC in its own write block.
        to_sync = [self.desc.tmp_path(c) for c in comps
                   if c not in (Component.TOC, Component.DATA)]
        sync_errs: list[OSError] = []

        def _sync(p):
            try:
                self._fsync_path(p)
            except OSError as e:
                sync_errs.append(e)

        if len(to_sync) > 1:
            ts = [threading.Thread(target=_sync, args=(p,))
                  for p in to_sync]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        else:
            for p in to_sync:
                _sync(p)
        if sync_errs:
            raise sync_errs[0]
        for comp in comps:
            if comp != Component.TOC:
                os.replace(self.desc.tmp_path(comp), self.desc.path(comp))
        # component renames must be durable BEFORE the TOC commit point
        # lands, and the TOC rename itself needs a second dir sync
        self._fsync_path(self.desc.directory)
        os.replace(self.desc.tmp_path(Component.TOC),
                   self.desc.path(Component.TOC))
        self._fsync_path(self.desc.directory)
        self._finished = True
        return stats

    def _ensure_alloc(self, end: int) -> None:
        if end <= self._allocated:
            return
        new_alloc = end + self.PREALLOC_BYTES
        try:
            os.posix_fallocate(self._data.fileno(), self._allocated,
                               new_alloc - self._allocated)
            self._allocated = new_alloc
        except OSError:
            # fs without fallocate support: fall back to plain extend
            self._allocated = 1 << 62

    def _acct(self, key: str, dt: float) -> None:
        if self.prof is not None:
            self.prof[key] = self.prof.get(key, 0.0) + dt
        if self._ledger is not None:
            st = self._ledger.get(key)
            if st is not None:
                st.add_busy(dt)

    def _write_all(self, mv: memoryview, reclaim=None) -> None:
        """Hand a compressed run of bytes to the data file. In threaded
        mode ownership of `reclaim` (the pack scratch backing mv) moves
        to the I/O thread and returns via the free pool — zero copy;
        without a reclaimable buffer the bytes are copied onto the
        queue. Otherwise written synchronously."""
        if self._threaded_io:
            if self._io_error:
                raise self._io_error[0]   # fail the producer fast
            if self._io_thread is None:
                self._io_thread = threading.Thread(
                    target=self._io_loop, name="sstable-io", daemon=True)
                self._io_thread.start()
            self._wq.put((mv if reclaim is not None else bytes(mv),
                          reclaim))
            if self._ledger is not None:
                self._ledger["io_write"].note_queue(self._wq.qsize())
            return
        t0 = time.perf_counter()
        self._write_sync(mv)
        self._acct("io_write", time.perf_counter() - t0)

    def _steal_wait(self, take_nowait, take_blocking):
        """Producer-side wait with caller work-stealing: while the
        wanted resource is unavailable, run queued pack jobs inline
        (CompressorPool.try_run_one) instead of sleeping — the blocked
        producer is an idle core and the jobs it runs are exactly what
        unblocks it. Returns (value, genuine_stall_seconds): time spent
        stealing is compress BUSY work (billed by the pool's pack
        stage), not backpressure, so only the blocking remainder counts
        as stall."""
        stall = 0.0
        while True:
            try:
                return take_nowait(), stall
            except queue.Empty:
                pass
            if self._io_error:
                raise self._io_error[0]
            if self._cpool is not None and self._cpool.try_run_one():
                continue
            t0 = time.perf_counter()
            try:
                return take_blocking(), \
                    stall + time.perf_counter() - t0
            except queue.Empty:
                stall += time.perf_counter() - t0

    def _take_pack_buf(self, need: int) -> "np.ndarray":
        """Borrow a pack buffer from the free pool (blocks when all are
        in flight — the pipeline's backpressure), growing it if this
        segment needs more room. An empty pool means the producer
        outran compress+disk: it steals queued pack jobs while waiting
        and the un-stolen remainder counts as a compress-stage stall."""
        try:
            buf = self._pack_free.get_nowait()
        except queue.Empty:
            if self._metrics is not None:
                self._metrics.incr("compress_stalls")
            buf, dt = self._steal_wait(
                self._pack_free.get_nowait,
                lambda: self._pack_free.get(timeout=0.05))
            if dt > 0 and self.prof is not None:
                # producer wall genuinely blocked on the write leg —
                # bench.py's write_phase attribution reads this
                self.prof["write_stall"] = \
                    self.prof.get("write_stall", 0.0) + dt
            if self._metrics is not None and dt > 0:
                self._metrics.hist("compress_stall").update_us(dt * 1e6)
                if self._ledger is not None:
                    # producer blocked on the compress+io stages: the
                    # backpressure seconds the ledger attributes to the
                    # stage being waited ON
                    self._ledger["compress"].add_stall(dt)
        if buf.nbytes < need:
            buf = np.empty(need, dtype=np.uint8)
        return buf

    # ------------------------------------------- adaptive-skip decisions --

    def _decide_attempt(self) -> list[bool]:
        """Attempt-compression flags for the next segment's three block
        streams. The skip machine folds in COMPLETED outcomes strictly
        lagged SKIP_DECISION_LAG segments behind the decision point —
        in serial mode every outcome is long since available; in
        parallel mode the lag is exactly the pipeline depth the pool
        may run ahead. Because both modes fold the same (decision_k,
        outcome_{k-LAG}) sequence, the decisions — and therefore the
        stored bytes — are identical for any pool size."""
        k = self._seq_submitted
        stall_s = 0.0
        stalled = False
        while self._seq_applied <= k - self.SKIP_DECISION_LAG:
            if self._io_error:
                raise self._io_error[0]
            try:
                out = self._acct_outcomes.get_nowait()
            except queue.Empty:
                # genuine lag: LAG segments in flight, oldest not done —
                # steal queued pack jobs while waiting (the oldest job
                # may be sitting un-started in the pool queue)
                if not stalled:
                    stalled = True
                    if self._metrics is not None:
                        self._metrics.incr("compress_stalls")
                out, dt = self._steal_wait(
                    self._acct_outcomes.get_nowait,
                    lambda: self._acct_outcomes.get(timeout=0.05))
                stall_s += dt
            if out is _ACCT_FAILED:
                raise self._io_error[0] if self._io_error else \
                    RuntimeError("compress pipeline failed")
            self._apply_outcome(out)
            self._seq_applied += 1
        if stall_s > 0:
            if self.prof is not None:
                self.prof["write_stall"] = \
                    self.prof.get("write_stall", 0.0) + stall_s
            if self._ledger is not None:
                self._ledger["compress"].add_stall(stall_s)
        attempt = []
        for i in range(3):
            if self._skip_left[i] > 0:
                self._skip_left[i] -= 1
                attempt.append(False)
            else:
                attempt.append(True)
        self._seq_submitted += 1
        return attempt

    def _apply_outcome(self, outcome) -> None:
        """Fold one segment's (stored, raw_len, attempted) per-stream
        outcome into the skip machine. A POOR ratio counts toward the
        skip streak — e.g. zstd squeezes 4.5% out of random framed
        blobs at ~155 MiB/s; 26ms per segment to save 4.5% is a bad
        trade. A raw store always satisfies the ratio test."""
        for i, (stored, raw_len, attempted) in enumerate(outcome):
            if not attempted:
                continue
            if stored * 10 > raw_len * 9:
                self._raw_streak[i] += 1
                if self._raw_streak[i] >= 4:
                    self._skip_left[i] = 15
            else:
                self._raw_streak[i] = 0

    def _fold_block(self, stored: int, raw_len: int, crc: int) -> bytes:
        """Per-block sequential bookkeeping: the index-entry triple and
        the digest fold (digest = crc32 over the per-block crc words —
        every byte is covered via its block crc without a second full
        pass). Runs on whichever single thread owns segment order in
        the current mode (producer, or the ordered completion loop)."""
        self._data_crc = zlib.crc32(struct.pack("<I", crc),
                                    self._data_crc)
        return struct.pack("<QQI", stored, raw_len, crc)

    def _device_compress_now(self) -> bool:
        """Whether the NEXT segment should arrive device-compressed:
        the gate the device write lane consults per segment. Callable
        gates (the hot-reloadable `compaction_device_compress` knob)
        re-read here, so a mid-compaction flip moves the compress work
        between device and host at a segment boundary without touching
        output bytes. Only the LZ4 policy codec has a device twin."""
        dc = self.device_compress
        if not dc:
            return False
        if self._packer is None or getattr(self._packer, "_cid", 0) != 1:
            return False
        return bool(dc() if callable(dc) else dc)

    # --------------------------------------------- parallel compress leg --

    def _submit_pack(self, blocks: list, attempt: list[bool],
                     need: int, n: int, lane_head: bytes,
                     lane_tail: bytes) -> None:
        """Hand one segment to the compressor pool; its index entry,
        digest fold and disk write happen on the ordered completion
        thread when its turn comes."""
        if self._io_error:
            raise self._io_error[0]   # fail the producer fast
        if self._io_thread is None:
            self._io_thread = threading.Thread(
                target=self._io_loop, name="sstable-io", daemon=True)
            self._io_thread.start()
        buf = self._take_pack_buf(need)
        if self._ledger is not None:
            self._ledger["compress"].add_items(1, need)
        job = _PackJob(self._seq_submitted - 1, blocks, attempt, buf,
                       n, lane_head, lane_tail)
        if self._metrics is not None:
            # per-consumer segment counter + stall hist live here;
            # queue depth is the POOL's gauge (compress_pool.queue_depth)
            # — a histogram of a dimensionless depth would come out
            # log2-quantized under a _us unit
            self._metrics.incr("compress_segments")
        # pack jobs become trace events when the producing statement is
        # traced (an inline threshold flush under a traced write): the
        # submit lands here, the completion on the ordered I/O thread
        from ...service import tracing
        job.trace = tracing.active()
        if job.trace is not None:
            job.trace.add(f"Compress pool: segment {job.seq} submitted "
                          f"({job.n} cells)")
        self._cpool.submit(lambda: self._run_pack_job(job))
        self._wq.put(job)   # single producer: queue order == seq order
        if self._ledger is not None:
            self._ledger["compress"].note_queue(self._wq.qsize())

    def _submit_packed(self, blocks: list, attempt: list[bool],
                       need: int, n: int, lane_head: bytes,
                       lane_tail: bytes, packed, t0: float) -> None:
        """Enqueue a segment the device already compressed: the job
        enters the SAME ordered completion queue as pool jobs, born
        finished (ready pre-set, stored bytes staged in a pack buffer),
        so device-compressed and pool-compressed segments interleave in
        submit order and the completion thread cannot tell them apart
        — entry/digest/write bookkeeping is one code path."""
        if self._io_error:
            raise self._io_error[0]   # fail the producer fast
        if self._io_thread is None:
            self._io_thread = threading.Thread(
                target=self._io_loop, name="sstable-io", daemon=True)
            self._io_thread.start()
        if faultfs.GLOBAL.active:
            # same checkpoint the pool workers honour: an injected EIO
            # must fail the device-compress leg like a real fault, and
            # unwind through the task's txn rollback
            faultfs.GLOBAL.check("sstable.compress", self._data_path)
        total, sizes, crcs, parts = packed
        buf = self._take_pack_buf(need)
        if self._ledger is not None:
            self._ledger["compress"].add_items(1, need)
        off = 0
        for p in parts:
            ln = len(p)
            buf[off:off + ln] = np.frombuffer(p, dtype=np.uint8)
            off += ln
        job = _PackJob(self._seq_submitted - 1, blocks, attempt, buf,
                       n, lane_head, lane_tail)
        if self._metrics is not None:
            self._metrics.incr("compress_segments")
            self._metrics.incr("device_compress_segments")
        from ...service import tracing
        job.trace = tracing.active()
        if job.trace is not None:
            job.trace.add(f"Device compress: segment {job.seq} arrived "
                          f"finished ({job.n} cells)")
        job.total = int(total)
        job.sizes = sizes
        job.crcs = crcs
        job.compress_s = time.perf_counter() - t0
        job.blocks = None
        job.ready.set()
        self._wq.put(job)   # single producer: queue order == seq order
        if self._ledger is not None:
            self._ledger["compress"].note_queue(self._wq.qsize())

    def _run_pack_job(self, job: _PackJob) -> None:
        """Pool-worker side: pack (delta + compress-or-raw + CRC) one
        segment into its buffer. Errors land in the job and surface on
        the completion thread exactly like a serial compress error."""
        try:
            hook = _TEST_SEGMENT_DELAY
            if hook is not None:
                hook(job.seq)
            if faultfs.GLOBAL.active:
                # sstable.compress checkpoint: an injected EIO here must
                # fail the writer like a real compressor/allocator fault
                faultfs.GLOBAL.check("sstable.compress", self._data_path)
            t0 = time.perf_counter()
            total, sizes, _raws, crcs = self._packer.pack(
                job.blocks, job.attempt, self.params.max_compressed_length,
                shuffle_block=1, lane_width=self.K, out=job.buf)
            job.total = total
            job.sizes = sizes
            job.crcs = crcs
            job.compress_s = time.perf_counter() - t0
        except BaseException as e:
            job.error = e
        finally:
            job.blocks = None   # drop ndarray refs as soon as packed
            job.ready.set()

    def _io_loop_ordered(self) -> None:
        """Ordered completion stage of the parallel-compress pipeline:
        jobs leave the pool in ANY order; this thread consumes them in
        SUBMIT order, so every sequential piece of writer state — file
        offsets, index entries, the digest fold, the skip-machine
        outcome stream — sees segments exactly as the serial writer
        would. Byte-identity for any pool size follows."""
        job = None
        try:
            while True:
                job = self._wq.get()
                if job is None:
                    return
                # waiting on the head job means compress is the
                # bottleneck RIGHT NOW — this otherwise-idle thread
                # steals queued pack jobs (possibly the very one it
                # waits on) instead of sleeping; the disk never idles
                # behind a ready job because stealing only happens
                # while the head is NOT ready
                while not job.ready.is_set():
                    if not self._cpool.try_run_one():
                        job.ready.wait(0.02)
                if job.error is not None:
                    raise job.error
                entry = struct.pack("<QI", self._data_off, job.n)
                outcome = []
                for i in range(3):
                    stored = int(job.sizes[i])
                    entry += self._fold_block(stored, job.raw_lens[i],
                                              int(job.crcs[i]))
                    outcome.append((stored, job.raw_lens[i],
                                    job.attempt[i]))
                entry += job.lane_head + job.lane_tail
                self._index_entries.append(entry)
                self._acct_outcomes.put(tuple(outcome))
                self._acct("compress", job.compress_s)
                if job.trace is not None:
                    job.trace.add(
                        f"Compress pool: segment {job.seq} packed "
                        f"({job.total} bytes, "
                        f"{job.compress_s * 1e3:.1f} ms)")
                t0 = time.perf_counter()
                self._write_sync(memoryview(job.buf)[:job.total])
                self._acct("io_write", time.perf_counter() - t0)
                self._data_off += job.total
                self._published_off = self._data_off
                self._pack_free.put(job.buf)
                job = None
        except BaseException as e:
            self._io_error.append(e)
            # wake a producer parked on the outcome stream, then return
            # every pack buffer (the failed job's included) and drain:
            # the producer must block on neither the pool nor the queue
            # — it surfaces the error at its next submit or at finish()
            self._acct_outcomes.put(_ACCT_FAILED)
            if job is not None:
                job.ready.wait()
                self._pack_free.put(job.buf)
            while True:
                job = self._wq.get()
                if job is None:
                    return
                job.ready.wait()
                self._pack_free.put(job.buf)

    def _io_loop(self) -> None:
        if self._cpool is not None:
            self._io_loop_ordered()
            return
        item = None
        try:
            while True:
                item = self._wq.get()
                if item is None:
                    return
                buf, reclaim = item
                t0 = time.perf_counter()
                self._write_sync(memoryview(buf) if not
                                 isinstance(buf, memoryview) else buf)
                self._acct("io_write", time.perf_counter() - t0)
                if reclaim is not None:
                    self._pack_free.put(reclaim)
        except BaseException as e:
            self._io_error.append(e)
            # return every owned scratch buffer (including the one whose
            # write just failed) and drain: the producer must block on
            # neither the pool nor the queue — it surfaces the error at
            # its next _write_all
            if item is not None and item[1] is not None:
                self._pack_free.put(item[1])
            while True:
                item = self._wq.get()
                if item is None:
                    return
                if item[1] is not None:
                    self._pack_free.put(item[1])

    def _stop_io_thread(self) -> None:
        if self._io_thread is None:
            return
        self._wq.put(None)
        if self._cpool is not None:
            # seal drain: the producer is done producing and about to
            # park in join() — steal queued pack jobs instead (the
            # un-overlapped end of the pipeline was a measured chunk of
            # the `seal` phase; two threads drain it in half the wall).
            # Bounded by OUR io thread's lifetime: it exits right after
            # this writer's tail completes, so a busy co-tenant's job
            # stream can extend this loop by at most one stolen job —
            # never unboundedly.
            while self._io_thread.is_alive() and self._cpool.try_run_one():
                pass
        self._io_thread.join()
        self._io_thread = None
        if self._io_error:
            raise self._io_error[0]

    def _write_sync(self, mv: memoryview) -> None:
        fault_after = None
        if faultfs.GLOBAL.active:
            # flush.write checkpoint: error mode raises here (nothing
            # lands), torn_write persists a prefix then raises from the
            # tail of this call, bitflip corrupts the bytes in flight —
            # the reader-side CRCs must catch it
            mv, fault_after = faultfs.GLOBAL.on_write(
                "flush.write", self._data_path, mv)
        total = mv.nbytes
        if self._ledger is not None:
            self._ledger["io_write"].add_items(1, total)
        self._ensure_alloc(self._written_off + total)
        self._written_off += total
        if self._direct:
            # stage into the aligned bounce buffer; flush full buffers
            # (BOUNCE_BYTES is a multiple of DIRECT_ALIGN, so steady-state
            # flushes are always aligned and leave no remainder)
            while mv.nbytes:
                take = min(self.BOUNCE_BYTES - self._bounce_fill, mv.nbytes)
                self._bounce_mv[self._bounce_fill:
                                self._bounce_fill + take] = mv[:take]
                self._bounce_fill += take
                mv = mv[take:]
                if self._bounce_fill == self.BOUNCE_BYTES:
                    self._flush_bounce()
            if fault_after is not None:
                raise fault_after
            return
        # buffered fallback: raw FileIO.write may write short (and caps
        # single writes around 2 GiB on Linux) — loop until all lands
        while mv.nbytes:
            n = self._data.write(mv)
            if n is None or n <= 0:
                raise OSError("short write to Data.db")
            mv = mv[n:]
        if fault_after is not None:
            raise fault_after
        self._bytes_since_sync += total
        if self._bytes_since_sync >= self.TRICKLE_FSYNC_BYTES:
            self._bytes_since_sync = 0
            if self._syncer is None:
                self._syncer = threading.Thread(
                    target=self._trickle_sync, daemon=True,
                    name="sstable-trickle-fsync")
                self._syncer.start()
            self._sync_req.set()       # syncer flushes in the background

    def _flush_bounce(self, final: bool = False) -> None:
        end = self._bounce_fill
        if final:
            aligned = -(-end // self.DIRECT_ALIGN) * self.DIRECT_ALIGN
            if aligned > end:   # zero-pad; finish() truncates back
                self._bounce_mv[end:aligned] = bytes(aligned - end)
            end = aligned
        pos = 0
        while pos < end:
            n = self._data.write(self._bounce_mv[pos:end])
            if n is None or n <= 0:
                raise OSError("short write to Data.db")
            if n % self.DIRECT_ALIGN and pos + n < end:
                raise OSError("misaligned partial O_DIRECT write")
            pos += n
        self._bounce_fill = 0

    def _trickle_sync(self) -> None:
        while True:
            self._sync_req.wait()
            self._sync_req.clear()
            if self._sync_stop:
                return
            try:
                os.fsync(self._data.fileno())
            except Exception as e:
                # a writeback error (EIO/ENOSPC) — or a racing close
                # (ValueError: fd already gone) — is reported ONCE per
                # fd; swallowing it here would let finish()'s final
                # fsync succeed and commit an sstable with lost pages.
                # Record it — finish() re-raises before the commit
                # point — instead of silently ending the trickle-sync
                # thread (ctpulint worker-loops).
                self._sync_error = e
                return

    def _stop_syncer(self) -> None:
        # join blocks for at most one in-flight fsync, bounded by
        # TRICKLE_FSYNC_BYTES of dirty pages (~0.15s on this disk)
        if self._syncer is None:
            return
        self._sync_stop = True
        self._sync_req.set()
        self._syncer.join()

    @staticmethod
    def _fsync_path(path: str) -> None:
        """fsync a file or directory by path (directories need an fd too —
        the rename itself is only durable once the dir entry is synced)."""
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def abort(self) -> None:
        if self._io_thread is not None:   # stop without raising
            self._wq.put(None)
            self._io_thread.join(timeout=30.0)
            self._io_thread = None
        self._stop_syncer()
        if not self._data.closed:
            self._data.close()
        if self._direct and not self._bounce.closed:
            self._bounce_mv.release()
            self._bounce.close()
        for comp in Component.ALL + Component.OPTIONAL:
            p = self.desc.tmp_path(comp)
            if os.path.exists(p):
                os.remove(p)

    # ------------------------------------------------------------ internals

    def _take(self, n: int) -> CellBatch:
        """Pop exactly n cells from pending batches."""
        taken = []
        got = 0
        while got < n:
            b = self._pending[0]
            need = n - got
            if len(b) <= need:
                taken.append(b)
                self._pending.pop(0)
                got += len(b)
            else:
                taken.append(b.slice_range(0, need))
                self._pending[0] = b.slice_range(need, len(b))
                got = n
        self._pending_cells -= n
        return CellBatch.concat(taken) if len(taken) > 1 else taken[0]

    def _cut_segment(self, n: int) -> None:
        seg = self._take(n)
        # --- blocks: vectorized serialization into one scratch buffer,
        # then zero-copy scatter-gather compression (the previous
        # tobytes/join/ctypes staging copied every byte ~4x — measured as
        # the dominant write-path cost)
        # "ce" meta layout (build_meta_block): ts-delta 8 + ldt 4 +
        # ttl 4 + flags 1 + frame_len u32 + val_rel u32 = 25 B/cell.
        # Frame lengths are the off deltas and val_rel the value offset
        # inside each frame — half the bytes of the absolute i64 pair
        # they replace, and far more compressible (small near-constant
        # integers); the ts lane is delta'd per segment for the same
        # reason (format.py "ce")
        t_ser = time.perf_counter()
        deltas = seg.off[1:] - seg.off[:-1]
        vrel64 = seg.val_start - seg.off[:-1]
        if len(deltas) and (int(deltas.max()) >= 1 << 32
                            or int(vrel64.max()) >= 1 << 32):
            # u32 lanes cannot hold a >=4GiB frame — fail loudly
            # instead of wrapping into silent corruption
            raise ValueError(
                f"cell frame exceeds the u32 offset lane "
                f"(max frame {int(deltas.max())} bytes)")
        meta = build_meta_block(seg.ts.astype(np.int64, copy=False),
                                seg.ldt, seg.ttl, seg.flags,
                                deltas.astype("<u4"),
                                vrel64.astype("<u4"))
        payload_b = np.ascontiguousarray(seg.payload)
        lanes_c = np.ascontiguousarray(seg.lanes)
        from ..cellbatch import DEATH_FLAGS
        seg_stats = (int(seg.ts.min()), int(seg.ts.max()),
                     int(seg.ldt.min()), int(seg.ldt.max()),
                     int(((seg.flags & DEATH_FLAGS) != 0).sum()))
        self._acct("serialize", time.perf_counter() - t_ser)
        self._emit_segment(n, meta, lanes_c, payload_b, seg.pk_map,
                           seg_stats)

    def _accumulate_zone(self, n: int, meta: "np.ndarray",
                         lanes_c: "np.ndarray",
                         payload_b: "np.ndarray") -> None:
        """Fold one segment's per-column (min key, max key, live, dead)
        zone entries from the already-serialized blocks — the cells are
        in META/LANES form here whichever leg built them, so this is
        the one place that covers host, pooled and device serialize
        paths identically."""
        from ...ops import device_scan as _ds
        if self._zone_cols is None:
            self._zone_cols = _ds.zonemap_columns(self.table)
        if not self._zone_cols:
            self._zone_acc = None   # nothing to map for this schema
            return
        flags = meta[16 * n:17 * n]
        frame = meta[17 * n:21 * n].copy().view("<u4").astype(np.int64)
        vrel = meta[21 * n:25 * n].copy().view("<u4").astype(np.int64)
        off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(frame, out=off[1:])
        C = lanes_c.shape[1] - 9
        self._zone_acc.append(_ds.segment_zone_entries(
            self._zone_cols, lanes_c[:, 6 + C], flags,
            off[:-1] + vrel, off[1:], payload_b))

    def _write_zonemap(self) -> None:
        """ZoneMap.db, written to its FINAL path outside the TOC (the
        attached-index contract: a missing/stale component is rebuilt
        from the sstable, so it needs no commit-point coupling)."""
        if self._zone_acc is None or self._zone_cols is None \
                or not self._zone_cols:
            return
        from ...index import sstable_index as ssi
        ssi.write_zonemap(ssi.zonemap_path(self.desc),
                          self._zone_cols, self._zone_acc)

    def _emit_segment(self, n: int, meta: "np.ndarray",
                      lanes_c: "np.ndarray", payload_b: "np.ndarray",
                      pk_map: dict, seg_stats: tuple,
                      device_pack=None) -> None:
        """Everything downstream of block serialization for ONE segment:
        ordering guards, partition directory + bloom, stats fold,
        adaptive-skip attempt decision, compress (pool / serial / the
        per-block fallback), index entry and digest bookkeeping. The
        host path enters from _cut_segment with blocks it built in
        numpy; the device-resident lane (ops/device_write.py) enters
        with blocks its fused kernel built from device arrays — one
        tail, so the two paths cannot diverge on any sequential writer
        state. seg_stats: (min_ts, max_ts, min_ldt, max_ldt,
        tombstones) computed by whichever side owned the columns.
        device_pack: optional (attempt, maxlen) -> (total, sizes,
        crcs, parts) closure from the device lane — the segment's
        blocks ALREADY policy-compressed on-device
        (ops/device_compress.pack_device_segment). Called after the
        skip-machine attempt decision so device and host legs consume
        identical attempt vectors; any failure falls back to the host
        compress leg for THIS segment (counted, never fatal, bytes
        identical)."""
        # cross-segment ordering guard; the intra-segment check runs
        # inside segment_pack's delta loop (fast path) or the numpy
        # comparison below (fallback path)
        first = lanes_c[0].astype(">u4").tobytes()
        if self._last_lane_end is not None and first < self._last_lane_end:
            raise ValueError("appended cells out of order")
        if n > 1 and self._packer is None:
            a, b = lanes_c[:-1], lanes_c[1:]
            neq = a != b
            anyneq = neq.any(axis=1)
            if anyneq.any():
                fi = neq.argmax(axis=1)
                rows = np.arange(n - 1)
                if ((a[rows, fi] > b[rows, fi]) & anyneq).any():
                    raise ValueError("appended cells out of order")

        # zone-map accumulation: once per segment, in append order, on
        # the appending thread — BEFORE the compress legs fork, so the
        # serial, pooled and device-packed paths all feed it
        if self._zone_acc is not None:
            self._accumulate_zone(n, meta, lanes_c, payload_b)

        # --- partition directory + bloom: one native pass over the
        # lanes finds the rows where the 4 pk lanes change (the numpy
        # strided slice-copy + row-compare this replaces was a measured
        # write-leg hotspot)
        starts = _part_starts(lanes_c, n)
        new_keys = []
        for s in starts:
            l4 = lanes_c[s, :4].astype(">u4").tobytes()
            if l4 == self._last_lane4:
                continue  # partition continues from previous segment
            pk = pk_map.get(l4)
            if pk is None:
                raise ValueError("pk_map missing partition key")
            self._part_lane4.append(l4)
            self._part_first_cell.append(self._total_cells + int(s))
            self._part_pk.append(pk)
            new_keys.append(pk)
            self._last_lane4 = l4
        self._bloom.add_batch(new_keys)

        # --- stats
        st = self._stats

        def _lo(key, v):
            st[key] = v if st[key] is None else min(st[key], v)

        def _hi(key, v):
            st[key] = v if st[key] is None else max(st[key], v)

        mn_ts, mx_ts, mn_ldt, mx_ldt, tombs = seg_stats
        _lo("min_ts", mn_ts)
        _hi("max_ts", mx_ts)
        _lo("min_ldt", mn_ldt)
        _hi("max_ldt", mx_ldt)
        self._stats["tombstones"] += tombs

        attempt = self._decide_attempt()
        maxlen = self.params.max_compressed_length
        lane_head = lanes_c[0].astype("<u4").tobytes()
        lane_tail = lanes_c[-1].astype("<u4").tobytes()
        t_pack = time.perf_counter()

        if self._packer is not None:
            # fused native path: delta + order check + compress-or-raw +
            # CRC + sequential placement, one GIL-released call
            blocks = [meta, lanes_c, payload_b]
            need = sum(b.nbytes for b in blocks)
            packed = None
            if device_pack is not None:
                try:
                    packed = device_pack(attempt, maxlen)
                except Exception:
                    # per-segment fallback: the host leg compresses this
                    # one; output bytes identical (same policy encoder)
                    if self._metrics is not None:
                        self._metrics.incr("device_compress_fallback")
                    packed = None
            if packed is not None and self._cpool is not None:
                self._submit_packed(blocks, attempt, need, n,
                                    lane_head, lane_tail, packed, t_pack)
                self._total_cells += n
                self._last_lane_end = lanes_c[-1].astype(">u4").tobytes()
                return
            if self._cpool is not None:
                # parallel leg: the pool compresses this segment while
                # this thread packs the NEXT one's lanes; the ordered
                # completion thread does entry/digest/write in seq
                # order (index entry + _total_cells stay consistent:
                # entries append in seq order over there, cells here)
                self._submit_pack(blocks, attempt, need, n,
                                  lane_head, lane_tail)
                self._total_cells += n
                self._last_lane_end = lanes_c[-1].astype(">u4").tobytes()
                return
            entry = struct.pack("<QI", self._data_off, n)
            if packed is not None:
                # device-compressed, serial/threaded completion: same
                # entry/digest/outcome bookkeeping as the native pack,
                # fed from the device lane's finished bytes
                total, sizes, crcs, parts = packed
                outcome = []
                for i in range(3):
                    stored = int(sizes[i])
                    entry += self._fold_block(stored, blocks[i].nbytes,
                                              int(crcs[i]))
                    outcome.append((stored, blocks[i].nbytes, attempt[i]))
                self._acct_outcomes.put(tuple(outcome))
                self._acct("compress", time.perf_counter() - t_pack)
                if self._ledger is not None:
                    self._ledger["compress"].add_items(1, need)
                if self._metrics is not None:
                    self._metrics.incr("device_compress_segments")
                self._write_all(memoryview(b"".join(parts)))
                self._data_off += int(total)
                self._published_off = self._data_off
            else:
                if self._threaded_io:
                    out = self._take_pack_buf(need)
                else:
                    if self._pack_out is None or self._pack_out.nbytes < need:
                        self._pack_out = np.empty(need, dtype=np.uint8)
                    out = self._pack_out
                total, sizes, raws, crcs = self._packer.pack(
                    blocks, attempt, maxlen, shuffle_block=1,
                    lane_width=lanes_c.shape[1], out=out)
                outcome = []
                for i in range(3):
                    stored = int(sizes[i])
                    entry += self._fold_block(stored, blocks[i].nbytes,
                                              int(crcs[i]))
                    outcome.append((stored, blocks[i].nbytes, attempt[i]))
                self._acct_outcomes.put(tuple(outcome))
                self._acct("compress", time.perf_counter() - t_pack)
                if self._ledger is not None:
                    self._ledger["compress"].add_items(1, need)
                self._write_all(memoryview(out)[:total],
                                reclaim=out if self._threaded_io else None)
                self._data_off += total
                self._published_off = self._data_off
        else:
            # per-block fallback (encrypted tables / codecs without a
            # native id). Lanes are still byte-plane shuffled — the
            # on-disk format is identical either way.
            entry = struct.pack("<QI", self._data_off, n)
            lanes_b = lanes_shuffle(
                lanes_c.astype(np.uint32, copy=False))
            blocks = [meta, lanes_b, payload_b]
            tried = [b for b, a in zip(blocks, attempt) if a]
            dst, dst_offs, sizes = self.compressor.compress_iov(tried)
            self._acct("compress", time.perf_counter() - t_pack)
            # min_compress_ratio fallback: store uncompressed when too
            # poor (CompressedSequentialWriter.java:160-175 semantics)
            ti = 0
            outcome = []
            for i, raw in enumerate(blocks):
                if attempt[i]:
                    c = dst[int(dst_offs[ti]):
                            int(dst_offs[ti]) + int(sizes[ti])]
                    ti += 1
                    if c.nbytes >= min(raw.nbytes, maxlen):
                        c = raw
                else:
                    c = raw
                mv = memoryview(c).cast("B")
                if self._enc is not None:
                    ctx, kid, nonces = self._enc
                    mv = memoryview(ctx.xor_at(kid, nonces[Component.DATA],
                                               self._data_off, mv))
                crc = zlib.crc32(mv)
                entry += self._fold_block(c.nbytes, raw.nbytes, crc)
                outcome.append((c.nbytes, raw.nbytes, attempt[i]))
                self._write_all(mv)
                self._data_off += c.nbytes
            self._acct_outcomes.put(tuple(outcome))
            self._published_off = self._data_off
        entry += lane_head
        entry += lane_tail
        self._index_entries.append(entry)
        self._total_cells += n
        self._last_lane_end = lanes_c[-1].astype(">u4").tobytes()

    _last_lane_end: bytes | None = None

    def _write_component(self, comp: str, data: bytes) -> None:
        """Write a small component, encrypting payload-bearing ones on
        encrypted tables (whole-file keystream from offset 0)."""
        if self._enc is not None:
            ctx, kid, nonces = self._enc
            if comp in nonces:
                data = ctx.xor_at(kid, nonces[comp], 0, data)
        with open(self.desc.tmp_path(comp), "wb") as f:
            f.write(data)

    def _write_index(self) -> None:
        out = bytearray(struct.pack("<III", len(self._index_entries),
                                    self.K, self.segment_cells))
        for e in self._index_entries:
            out += e
        self._write_component(Component.INDEX, bytes(out))

    def _write_partitions(self) -> None:
        np_count = len(self._part_lane4)
        out = bytearray(struct.pack("<I", np_count))
        out += b"".join(self._part_lane4)
        out += np.array(self._part_first_cell, dtype="<i8").tobytes()
        pk_off = np.zeros(np_count + 1, dtype="<i8")
        np.cumsum([len(p) for p in self._part_pk], out=pk_off[1:])
        out += pk_off.tobytes()
        out += b"".join(self._part_pk)
        self._write_component(Component.PARTITIONS, bytes(out))

    def _write_filter(self) -> None:
        with open(self.desc.tmp_path(Component.FILTER), "wb") as f:
            f.write(self._bloom.serialize())

    def _write_stats(self) -> dict:
        stats = {
            "version": self.desc.version,
            "keyspace": self.table.keyspace,
            "table": self.table.name,
            "table_id": str(self.table.id),
            "n_lanes": self.K,
            "segment_cells": self.segment_cells,
            "n_cells": self._total_cells,
            "n_partitions": len(self._part_lane4),
            "compression": self.params.to_dict(),
            "level": self.level,
            "repaired_at": self.repaired_at,
            "ck_fits_prefix": self._ck_fits,
            **self._stats,
        }
        with open(self.desc.tmp_path(Component.STATS), "w") as f:
            json.dump(stats, f)
        return stats

    def _write_digest(self) -> None:
        with open(self.desc.tmp_path(Component.DIGEST), "w") as f:
            f.write(f"{self._data_crc & 0xFFFFFFFF}\n")
