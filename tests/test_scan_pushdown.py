"""Device-accelerated analytical scans: flush-time zone maps (ZMP1),
fused filter kernels vs the host numpy reference, mesh-fanned Phase A,
and the ALLOW FILTERING pushdown lane (reference counterparts: SAI
metadata pruning in index/sai/* + partition-restricted range reads).

The load-bearing invariant everywhere below: the device leg, the host
leg, the mesh legs and the naive Python scan are BIT-IDENTICAL —
pushdown is a latency optimization, never a semantics change."""
import os

import numpy as np
import pytest

from cassandra_tpu.config import Config, Settings
from cassandra_tpu.cql import Session
from cassandra_tpu.index import sstable_index as ssi
from cassandra_tpu.ops import device_scan as ds
from cassandra_tpu.schema import Schema
from cassandra_tpu.service.metrics import GLOBAL as METRICS
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.utils import faultfs, timeutil


@pytest.fixture(autouse=True)
def _clean_faults():
    faultfs.disarm()
    yield
    faultfs.disarm()


@pytest.fixture
def eng(tmp_path):
    e = StorageEngine(str(tmp_path / "data"), Schema(),
                      commitlog_sync="batch",
                      settings=Settings(Config.load(
                          {"disk_failure_policy": "best_effort"})))
    yield e
    e.close()


@pytest.fixture
def session(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    return s


def _pred(cfs, col, op, val):
    p = ds.compile_predicate(cfs.table, [(cfs.table.columns[col], op, val)])
    assert p is not None
    return p


def _pks(cfs, pred, **kw):
    out, info = cfs.scan_filtered(pred, **kw)
    return sorted(pk for pk, _b in out), info


# ------------------------------------------------------------ key space --

def test_scan_keys_are_monotone():
    """u64 scan keys preserve value order for every exact kind — the
    property every zone-prune rule and range kernel rests on."""
    ints = [-(1 << 63), -12345, -1, 0, 1, 7, (1 << 62), (1 << 63) - 1]
    ks = [ds.key_of_value("i64", v) for v in ints]
    assert ks == sorted(ks) and len(set(ks)) == len(ks)
    fls = [float("-inf"), -1e300, -2.5, -0.0, 0.0, 1e-300, 3.14,
           float("inf")]
    kf = [ds.key_of_value("f64", v) for v in fls]
    assert kf == sorted(kf)
    assert kf[3] == kf[4]          # -0.0 and +0.0 collapse (CQL equality)
    assert ds.key_of_value("bool", False) < ds.key_of_value("bool", True)
    assert ds.key_of_value("f64", float("nan")) is None
    # round trips
    for v in ints:
        assert ds.value_of_key("i64", ds.key_of_value("i64", v)) == v
    for v in (-2.5, 0.0, 3.14, float("inf")):
        assert ds.value_of_key("f64", ds.key_of_value("f64", v)) == v


def test_prefix_keys_superset_not_exact():
    """Text keys (8-byte big-endian prefix) order correctly and share a
    key only when the prefixes collide — the executor re-verifies, so
    superset is the contract, not equality."""
    a = ds.key_of_value("prefix", "apple")
    b = ds.key_of_value("prefix", "banana")
    assert a < b
    long1 = ds.key_of_value("prefix", "same-prefix-A")
    long2 = ds.key_of_value("prefix", "same-prefix-B")
    assert long1 == long2          # first 8 bytes identical: collision


# ----------------------------------------------------- zone map component --

def test_zonemap_written_at_flush_and_loads(session, eng):
    session.execute("CREATE TABLE zm (k int PRIMARY KEY, v int, t text)")
    for i in range(50):
        session.execute(f"INSERT INTO zm (k, v, t) VALUES ({i}, {i}, 'x{i}')")
    cfs = eng.store("ks", "zm")
    cfs.flush()
    (r,) = cfs.live_sstables()
    path = ssi.zonemap_path(r.desc)
    assert os.path.exists(path)
    zm = ssi.load_zonemap(path, expected_segments=r.n_segments)
    assert zm is not None and zm.n_segments == r.n_segments
    # both the int and the text column carry bounds
    vcid = cfs.table.columns["v"].column_id
    tcid = cfs.table.columns["t"].column_id
    assert vcid in zm.cols and tcid in zm.cols


def test_zonemap_rebuilds_after_corruption(session, eng):
    """EQI1 contract: a torn/garbage component is rebuilt from the
    decoded segments (counted), never trusted, never fatal."""
    session.execute("CREATE TABLE zr (k int PRIMARY KEY, v int)")
    for i in range(40):
        session.execute(f"INSERT INTO zr (k, v) VALUES ({i}, {i % 10})")
    cfs = eng.store("ks", "zr")
    cfs.flush()
    (r,) = cfs.live_sstables()
    path = ssi.zonemap_path(r.desc)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    before = METRICS.counter("scan.zonemap_rebuilds")
    got, _ = _pks(cfs, _pred(cfs, "v", "=", 3))
    assert METRICS.counter("scan.zonemap_rebuilds") > before
    assert len(got) == 4
    # the rebuild rewrote a parseable component
    assert ssi.load_zonemap(path, expected_segments=r.n_segments) is not None


# --------------------------------------------- kernel vs host identity --

def _seed_deletion_scopes(session, eng):
    session.execute("CREATE TABLE dt (k int, c int, v int, s text, "
                    "PRIMARY KEY (k, c))")
    for k in range(12):
        for c in range(4):
            session.execute(f"INSERT INTO dt (k, c, v, s) VALUES "
                            f"({k}, {c}, {k * 10 + c}, 'p{k % 3}')")
    session.execute("DELETE v FROM dt WHERE k = 1 AND c = 1")  # cell
    session.execute("DELETE FROM dt WHERE k = 2 AND c = 2")    # row
    session.execute("DELETE FROM dt WHERE k = 3")              # partition
    session.execute("DELETE FROM dt WHERE k = 4 AND c >= 2")   # range
    cfs = eng.store("ks", "dt")
    cfs.flush()
    # second generation with overwrites so reconciliation has work
    for k in range(6, 9):
        session.execute(f"INSERT INTO dt (k, c, v) VALUES ({k}, 0, "
                        f"{k * 10})")
    cfs.flush()
    return cfs


def test_kernel_vs_host_identity_across_deletion_scopes(session, eng):
    cfs = _seed_deletion_scopes(session, eng)
    for op, val in [("=", 10), (">", 30), ("<=", 25), ("!=", 42),
                    ("IN", [11, 23, 70])]:
        dev, _ = _pks(cfs, _pred(cfs, "v", op, val), use_device=True)
        host, _ = _pks(cfs, _pred(cfs, "v", op, val), use_device=False)
        assert dev == host, f"device/host diverged for v {op} {val}"
    # end-to-end: CQL rows identical under both gate pins
    q = ("SELECT k, c, v FROM dt WHERE v >= 20 AND v < 80 "
         "ALLOW FILTERING")
    eng.settings.set("scan_device_filter", True)
    dev_rows = session.execute(q).rows
    eng.settings.set("scan_device_filter", False)
    host_rows = session.execute(q).rows
    eng.settings.set("scan_device_filter", True)
    assert dev_rows == host_rows
    # deleted scopes really are invisible
    ks = {r[0] for r in dev_rows}
    assert 3 not in ks             # partition delete


def test_ttl_expiry_at_read_identity(session, eng):
    """Cells whose TTL lapses between write and read: Phase A may still
    nominate the partition (zone maps are write-time), Phase B + the
    executor drop it — and device == host at every now."""
    session.execute("CREATE TABLE tt (k int PRIMARY KEY, v int)")
    for i in range(10):
        session.execute(f"INSERT INTO tt (k, v) VALUES ({i}, {i}) "
                        f"USING TTL 100")
    session.execute("INSERT INTO tt (k, v) VALUES (50, 5)")  # immortal
    cfs = eng.store("ks", "tt")
    cfs.flush()
    pred = _pred(cfs, "v", "=", 5)
    now = timeutil.now_seconds()
    for when in (now, now + 1000):       # live, then all-TTL-expired
        dev, _ = _pks(cfs, pred, now=when, use_device=True)
        host, _ = _pks(cfs, pred, now=when, use_device=False)
        assert dev == host
    # after expiry only the immortal row still has a LIVE matching cell
    # in the reconciled merge (expired cells surface as tombstones)
    out, _ = cfs.scan_filtered(pred, now=now + 1000)
    live = [pk for pk, b in out
            if len(ds.batch_predicate_cells(b, pred, reconciled=True)[0])]
    assert live == [cfs.table.columns["k"].cql_type.serialize(50)]


def test_all_tombstone_segment_prunes(session, eng):
    """A flushed sstable holding only deletes has zero live cells in
    every zone: the scan skips all its segments without decoding."""
    session.execute("CREATE TABLE at (k int PRIMARY KEY, v int)")
    for i in range(20):
        session.execute(f"INSERT INTO at (k, v) VALUES ({i}, {i})")
    cfs = eng.store("ks", "at")
    cfs.flush()
    for i in range(20):
        session.execute(f"DELETE FROM at WHERE k = {i}")
    cfs.flush()                      # second sstable: tombstones only
    pred = _pred(cfs, "v", ">=", 0)
    got, info = _pks(cfs, pred)
    assert info["segments_skipped"] >= 1
    assert info["sstables_skipped"] >= 1
    # and correctness: everything is deleted
    assert session.execute(
        "SELECT k FROM at WHERE v >= 0 ALLOW FILTERING").rows == []


def test_min_eq_max_zone_boundaries(session, eng):
    """Constant-valued segments (kmin == kmax) exercise every strict /
    non-strict boundary in prune_keep_mask."""
    session.execute("CREATE TABLE mm (k int PRIMARY KEY, v int)")
    for i in range(30):
        session.execute(f"INSERT INTO mm (k, v) VALUES ({i}, 7)")
    cfs = eng.store("ks", "mm")
    cfs.flush()
    cases = [("=", 7, True), ("=", 8, False), ("<", 7, False),
             ("<=", 7, True), (">", 7, False), (">=", 7, True),
             ("!=", 7, False), ("IN", [6, 8], False), ("IN", [6, 7], True)]
    for op, val, any_kept in cases:
        got, info = _pks(cfs, _pred(cfs, "v", op, val))
        if any_kept:
            assert len(got) == 30, f"v {op} {val}"
        else:
            assert got == [], f"v {op} {val}"
            assert info["segments_skipped"] == info["segments_total"], \
                f"v {op} {val} decoded a provably-empty segment"


# ----------------------------------------------------- mesh + gate knob --

def test_mesh_and_serial_scans_identical(session, eng):
    session.execute("CREATE TABLE ms (k int PRIMARY KEY, v int, t text)")
    cfs = eng.store("ks", "ms")
    for i in range(200):
        session.execute(f"INSERT INTO ms (k, v, t) VALUES ({i}, {i % 17}, "
                        f"'w{i % 5}')")
        if i % 80 == 79:
            cfs.flush()
    cfs.flush()
    q = "SELECT k FROM ms WHERE v = 3 ALLOW FILTERING"
    legs = {}
    try:
        for n in (0, 1, 4):
            eng.settings.set("compaction_mesh_devices", n)
            legs[n] = sorted(session.execute(q).rows)
    finally:
        eng.settings.set("compaction_mesh_devices", 0)
    assert legs[0] == legs[1] == legs[4]
    assert len(legs[0]) == len([i for i in range(200) if i % 17 == 3])


def test_mesh_scan_counts_and_drains_token_order(session, eng):
    session.execute("CREATE TABLE mo (k int PRIMARY KEY, v int)")
    cfs = eng.store("ks", "mo")
    for i in range(150):
        session.execute(f"INSERT INTO mo (k, v) VALUES ({i}, {i % 2})")
    cfs.flush()
    pred = _pred(cfs, "v", "=", 1)
    serial, _ = cfs.scan_filtered(pred)
    try:
        eng.settings.set("compaction_mesh_devices", 2)
        before = METRICS.counter("scan.mesh_scans")
        meshed, info = cfs.scan_filtered(pred)
        fanned = METRICS.counter("scan.mesh_scans") > before
    finally:
        eng.settings.set("compaction_mesh_devices", 0)
    assert [pk for pk, _ in meshed] == [pk for pk, _ in serial]
    if fanned:                       # boundaries existed: shards ran
        assert info["segments_total"] >= 1


def test_mid_scan_gate_flip(session, eng):
    """A callable gate is consulted per segment: flipping it mid-scan
    moves later segments to the other leg with identical results."""
    session.execute("CREATE TABLE gf (k int PRIMARY KEY, v int)")
    cfs = eng.store("ks", "gf")
    for gen in range(3):
        for i in range(gen * 40, gen * 40 + 40):
            session.execute(f"INSERT INTO gf (k, v) VALUES ({i}, {i % 4})")
        cfs.flush()
    pred = _pred(cfs, "v", "=", 2)
    calls = [0]

    def flip():
        calls[0] += 1
        return calls[0] > 2          # host for 2 segments, then device

    flipped, info = cfs.scan_filtered(pred, use_device=flip)
    pinned, _ = cfs.scan_filtered(pred, use_device=True)
    assert [pk for pk, _ in flipped] == [pk for pk, _ in pinned]
    assert calls[0] >= 3             # gate re-read per segment
    assert info["host_segments"] >= 1
    assert info["device_segments"] + info["host_segments"] == calls[0]


# ------------------------------------------------------------ faults --

def test_eio_quarantines_per_source(session, eng):
    """EIO on one sstable mid-scan degrades THAT source (best_effort
    quarantine) — the other sstables' candidates still come back."""
    session.execute("CREATE TABLE io (k int PRIMARY KEY, v int)")
    cfs = eng.store("ks", "io")
    for i in range(30):
        session.execute(f"INSERT INTO io (k, v) VALUES ({i}, 1)")
    cfs.flush()
    for i in range(30, 60):
        session.execute(f"INSERT INTO io (k, v) VALUES ({i}, 1)")
    cfs.flush()
    gens = sorted(r.desc.generation for r in cfs.live_sstables())
    assert len(gens) == 2
    bad = gens[0]
    faultfs.arm("sstable.read", "error",
                path_substr=f"-{bad}-Data.db")
    try:
        got, _ = _pks(cfs, _pred(cfs, "v", "=", 1))
    finally:
        faultfs.disarm()
    live_gens = {r.desc.generation for r in cfs.live_sstables()}
    assert bad not in live_gens      # quarantined, not fatal
    assert len(got) >= 30            # healthy source fully scanned


# --------------------------------------------------------- eager index --

def test_eager_index_build_at_flush(session, eng):
    """An index created BEFORE data is flushed gets its component built
    in the flush tail (index.builds), not lazily at first query."""
    session.execute("CREATE TABLE ei (k int PRIMARY KEY, city text)")
    session.execute("CREATE INDEX ON ei (city)")
    cfs = eng.store("ks", "ei")
    for i in range(20):
        session.execute(f"INSERT INTO ei (k, city) VALUES ({i}, 'c{i % 3}')")
    b0 = METRICS.counter("index.builds")
    l0 = METRICS.counter("index.lazy_builds")
    cfs.flush()
    assert METRICS.counter("index.builds") > b0
    got = {r[0] for r in session.execute(
        "SELECT k FROM ei WHERE city = 'c1'").rows}
    assert got == {i for i in range(20) if i % 3 == 1}
    assert METRICS.counter("index.lazy_builds") == l0   # never lazy


def test_lazy_index_build_counted(session, eng):
    """An index created AFTER the flush has no component on the existing
    sstable: the first lookup builds it lazily (index.lazy_builds)."""
    session.execute("CREATE TABLE li (k int PRIMARY KEY, city text)")
    cfs = eng.store("ks", "li")
    for i in range(12):
        session.execute(f"INSERT INTO li (k, city) VALUES ({i}, 'c{i % 2}')")
    cfs.flush()
    session.execute("CREATE INDEX ON li (city)")
    l0 = METRICS.counter("index.lazy_builds")
    got = {r[0] for r in session.execute(
        "SELECT k FROM li WHERE city = 'c1'").rows}
    assert got == {i for i in range(12) if i % 2 == 1}
    assert METRICS.counter("index.lazy_builds") > l0


# --------------------------------------------------- pushdown counters --

def test_agg_pushdown_materializes_zero_rows(session, eng):
    session.execute("CREATE TABLE ag (k int PRIMARY KEY, v int)")
    cfs = eng.store("ks", "ag")
    for i in range(100):
        session.execute(f"INSERT INTO ag (k, v) VALUES ({i}, {i % 10})")
    cfs.flush()
    m0 = METRICS.counter("scan.rows_materialized")
    a0 = METRICS.counter("scan.agg_pushdown")
    rs = session.execute(
        "SELECT count(*) FROM ag WHERE v = 3 ALLOW FILTERING")
    assert rs.rows == [(10,)]
    rs = session.execute(
        "SELECT count(v), min(v), max(v), sum(v), avg(v) FROM ag "
        "WHERE v = 3 ALLOW FILTERING")
    assert rs.rows == [(10, 3, 3, 30, 3.0)]
    assert METRICS.counter("scan.agg_pushdown") == a0 + 2
    assert METRICS.counter("scan.rows_materialized") == m0, \
        "aggregate pushdown must not materialize row dicts"
    # empty-match aggregates: count 0, min/max None, sum 0
    rs = session.execute(
        "SELECT count(v), min(v), sum(v) FROM ag WHERE v = 99 "
        "ALLOW FILTERING")
    assert rs.rows == [(0, None, 0)]


def test_row_pushdown_and_fallback_counters(session, eng):
    session.execute("CREATE TABLE pf (k int PRIMARY KEY, v int, w varint)")
    cfs = eng.store("ks", "pf")
    for i in range(40):
        session.execute(f"INSERT INTO pf (k, v, w) VALUES ({i}, {i}, {i})")
    cfs.flush()
    p0 = METRICS.counter("scan.pushdown")
    f0 = METRICS.counter("scan.fallback")
    got = {r[0] for r in session.execute(
        "SELECT k FROM pf WHERE v < 5 ALLOW FILTERING").rows}
    assert got == set(range(5))
    assert METRICS.counter("scan.pushdown") == p0 + 1
    # varint has no scan-key kind: the Python scan answers, counted
    got = {r[0] for r in session.execute(
        "SELECT k FROM pf WHERE w = 7 ALLOW FILTERING").rows}
    assert got == {7}
    assert METRICS.counter("scan.fallback") == f0 + 1


def test_pushdown_respects_memtable_and_statics(session, eng):
    """Unflushed rows (no zone maps) and static columns both flow
    through the pushdown lane unchanged."""
    session.execute("CREATE TABLE st (k int, c int, s text STATIC, "
                    "v int, PRIMARY KEY (k, c))")
    cfs = eng.store("ks", "st")
    for k in range(8):
        session.execute(f"INSERT INTO st (k, s) VALUES ({k}, 'g{k % 2}')")
        for c in range(3):
            session.execute(f"INSERT INTO st (k, c, v) VALUES "
                            f"({k}, {c}, {k * 10 + c})")
    cfs.flush()
    for k in range(8, 12):           # memtable-only partitions
        session.execute(f"INSERT INTO st (k, c, v) VALUES ({k}, 0, "
                        f"{k * 10})")
    got = {r[0] for r in session.execute(
        "SELECT k, c FROM st WHERE v >= 80 ALLOW FILTERING").rows}
    assert got == {8, 9, 10, 11}     # memtable rows found
    # static predicate: every row of matching partitions comes back
    rows = session.execute(
        "SELECT k, c FROM st WHERE s = 'g1' ALLOW FILTERING").rows
    assert {r[0] for r in rows} == {1, 3, 5, 7}
    assert len(rows) == 4 * 3


def test_in_and_text_prefix_predicates(session, eng):
    session.execute("CREATE TABLE tp (k int PRIMARY KEY, t text, v int)")
    cfs = eng.store("ks", "tp")
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    for i in range(50):
        session.execute(f"INSERT INTO tp (k, t, v) VALUES ({i}, "
                        f"'{words[i % 5]}-{i}', {i})")
    cfs.flush()
    got = {r[0] for r in session.execute(
        "SELECT k FROM tp WHERE t = 'beta-6' ALLOW FILTERING").rows}
    assert got == {6}
    got = {r[0] for r in session.execute(
        "SELECT k FROM tp WHERE v IN (3, 17, 44, 99) "
        "ALLOW FILTERING").rows}
    assert got == {3, 17, 44}
