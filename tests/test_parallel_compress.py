"""Parallel compression pipeline (storage/sstable/compress_pool.py +
SSTableWriter parallel-compress mode): ordered handoff under adversarial
completion order, byte-identity for any pool size, worker-EIO unwind
matching the serial compress error path, hot-resize mid-compaction,
decode-ahead equivalence, and sim determinism."""
from __future__ import annotations

import hashlib
import os
import threading
import time

import numpy as np
import pytest

from cassandra_tpu.schema import TableParams, make_table
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.sstable import (Descriptor, SSTableReader,
                                           SSTableWriter)
from cassandra_tpu.storage.sstable import writer as writer_mod
from cassandra_tpu.storage.sstable.compress_pool import (CompressorPool,
                                                         auto_workers,
                                                         get_pool)
from cassandra_tpu.tools import bulk
from cassandra_tpu.utils import faultfs


def _table(name="t"):
    return make_table("pk", name, pk=["id"], ck=["c"],
                      cols={"id": "int", "c": "int", "v": "blob"},
                      params=TableParams())


def _mixed_batch(table, seed=1, n=60_000, width=48):
    """Alternating compressible/incompressible partitions: crosses the
    adaptive-skip machine's engage/probe/disengage transitions, the
    case where decision order affects bytes."""
    rng = np.random.default_rng(seed)
    pk = rng.integers(0, 128, n)
    ck = rng.integers(0, 100_000, n)
    text = rng.integers(97, 122, (n, width), dtype=np.uint8)
    blob = rng.integers(0, 256, (n, width), dtype=np.uint8)
    vals = np.where((pk % 2 == 0)[:, None], text, blob)
    ts = rng.integers(1, 1 << 40, n).astype(np.int64)
    return cb.merge_sorted([bulk.build_int_batch(table, pk, ck, vals, ts)])


def _write(tmp_path, table, batch, tag, segment_cells=4096, **kw):
    d = str(tmp_path / tag)
    w = SSTableWriter(Descriptor(d, 1), table,
                      segment_cells=segment_cells, **kw)
    step = segment_cells + 123   # appends never align with segment cuts
    for i in range(0, len(batch), step):
        w.append(batch.slice_range(i, min(i + step, len(batch))))
    w.finish()
    return Descriptor(d, 1)


def _file_hashes(desc) -> dict:
    out = {}
    for comp in ("Data.db", "Index.db", "Partitions.db", "Digest.crc32"):
        with open(desc.path(comp), "rb") as f:
            out[comp] = hashlib.sha256(f.read()).hexdigest()
    return out


# ------------------------------------------------- ordered completion --

def test_adversarial_completion_order_resequenced(tmp_path, monkeypatch):
    """Workers finishing OUT of order (even segments delayed) must not
    change a byte: the ordered completion queue re-sequences them."""
    table = _table()
    batch = _mixed_batch(table)
    ref = _file_hashes(_write(tmp_path, table, batch, "ref"))

    def delay(seq):
        if seq % 2 == 0:
            time.sleep(0.02)   # odd successors complete first

    monkeypatch.setattr(writer_mod, "_TEST_SEGMENT_DELAY", delay)
    pool = CompressorPool(4)
    try:
        got = _file_hashes(_write(tmp_path, table, batch, "adv",
                                  compress_pool=pool))
    finally:
        pool.shutdown(timeout=5.0)
    assert got == ref


def test_pool_sizes_and_serial_byte_identical(tmp_path):
    """Serial, threaded, 1-worker and 3-worker pools: identical files
    (the fast inline version of scripts/check_compaction_ab.py)."""
    table = _table()
    batch = _mixed_batch(table)
    ref = _file_hashes(_write(tmp_path, table, batch, "serial"))
    assert _file_hashes(_write(tmp_path, table, batch, "thr",
                               threaded_io=True)) == ref
    for w in (1, 3):
        pool = CompressorPool(w)
        try:
            got = _file_hashes(_write(tmp_path, table, batch, f"p{w}",
                                      compress_pool=pool))
        finally:
            pool.shutdown(timeout=5.0)
        assert got == ref, f"pool size {w} diverged from serial bytes"


def test_parallel_output_readable_roundtrip(tmp_path):
    table = _table()
    batch = _mixed_batch(table, n=20_000)
    pool = CompressorPool(2)
    try:
        desc = _write(tmp_path, table, batch, "rt", compress_pool=pool)
    finally:
        pool.shutdown(timeout=5.0)
    r = SSTableReader(desc, table)
    got = cb.CellBatch.concat(list(r.scanner()))
    assert cb.content_digest(got) == cb.content_digest(batch)
    r.close()


# --------------------------------------------------------- EIO unwind --

def test_worker_eio_fails_writer_and_abort_cleans(tmp_path):
    """An injected EIO inside a pool worker must fail the writer
    exactly like a serial compress error: finish() raises, abort()
    leaves no tmp components behind."""
    table = _table()
    batch = _mixed_batch(table, n=30_000)
    pool = CompressorPool(2)
    d = str(tmp_path / "eio")
    w = SSTableWriter(Descriptor(d, 1), table, segment_cells=2048,
                      compress_pool=pool)
    try:
        with faultfs.inject("sstable.compress", "error", after=2):
            with pytest.raises(OSError):
                # the async error lands at a later append or at finish
                for i in range(0, len(batch), 2048):
                    w.append(batch.slice_range(i, min(i + 2048,
                                                      len(batch))))
                w.finish()
        w.abort()
    finally:
        pool.shutdown(timeout=5.0)
    leftovers = [f for f in os.listdir(d) if "tmp" in f]
    assert leftovers == []


def test_worker_eio_aborts_compaction_inputs_stay_live(tmp_path):
    """Worker EIO mid-compaction: the task aborts, the lifecycle txn
    rolls back, inputs keep serving (the PR-5 abort semantics hold
    through the parallel leg)."""
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _table()
    cfs = ColumnFamilyStore(table, str(tmp_path / "cfs"), commitlog=None)
    for gen in (1, 2):
        w = SSTableWriter(Descriptor(cfs.directory, gen), table)
        w.append(_mixed_batch(table, seed=gen, n=30_000))
        w.finish()
    cfs.reload_sstables()
    inputs = cfs.tracker.view()
    in_gens = {r.desc.generation for r in inputs}
    pool = CompressorPool(2)
    task = CompactionTask(cfs, inputs, compress_pool=pool,
                          round_cells=8192)
    try:
        with faultfs.inject("sstable.compress", "error"):
            with pytest.raises(OSError):
                task.execute()
    finally:
        pool.shutdown(timeout=5.0)
    live = {r.desc.generation for r in cfs.live_sstables()}
    assert live == in_gens, "rollback must keep exactly the inputs live"
    assert cb.content_digest(cfs.scan_all()) is not None  # still serves


def test_worker_eio_flush_restores_memtable(tmp_path, monkeypatch):
    """Worker EIO during a fast-lane flush: the flush fails through the
    failure policy funnel, the memtable is REINSTATED (acked writes
    stay readable), and a retry after the fault clears succeeds — the
    PR-5 flush-EIO recovery holds through the parallel compress leg."""
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    from cassandra_tpu.storage.cellbatch import FLAG_ROW_LIVENESS
    from cassandra_tpu.storage.mutation import Mutation
    from cassandra_tpu.storage.table import ColumnFamilyStore

    monkeypatch.setenv("CTPU_WRITE_FASTPATH", "1")
    table = _table()
    cfs = ColumnFamilyStore(table, str(tmp_path / "f"), commitlog=None)
    vcol = table.columns["v"].column_id
    for k in range(50):
        m = Mutation(table.id, table.serialize_partition_key([k]))
        ck = table.serialize_clustering([0])
        m.add(ck, COL_ROW_LIVENESS, b"", b"", 1000, flags=FLAG_ROW_LIVENESS)
        m.add(ck, vcol, b"", b"v%d" % k, 1000)
        cfs.apply(m)
    with faultfs.inject("sstable.compress", "error"):
        with pytest.raises(OSError):
            cfs.flush()
    assert not cfs.memtable.is_empty, "failed flush must restore memtable"
    assert len(cfs.read_partition(table.serialize_partition_key([7]))) > 0
    reader = cfs.flush()   # fault cleared: retry drains the same data
    assert reader is not None and reader.n_cells > 0
    assert len(cfs.read_partition(table.serialize_partition_key([7]))) > 0


def test_corrupt_input_quarantine_with_parallel_compress(tmp_path):
    """PR-5 corrupt-input handling through the parallel write leg: the
    task aborts itself and best_effort quarantines the rotten input."""
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.sstable.reader import CorruptSSTableError
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _table()
    cfs = ColumnFamilyStore(table, str(tmp_path / "cfs"), commitlog=None)
    for gen in (1, 2):
        w = SSTableWriter(Descriptor(cfs.directory, gen), table)
        w.append(_mixed_batch(table, seed=gen, n=30_000))
        w.finish()
    cfs.reload_sstables()
    inputs = cfs.tracker.view()
    pool = CompressorPool(2)
    task = CompactionTask(cfs, inputs, compress_pool=pool,
                          round_cells=8192)
    bad_path = inputs[0].desc.path("Data.db")
    try:
        with faultfs.inject("sstable.read", "bitflip",
                            path_substr=bad_path):
            with pytest.raises(CorruptSSTableError):
                task.execute()
    finally:
        pool.shutdown(timeout=5.0)
    live = {r.desc.generation for r in cfs.live_sstables()}
    assert inputs[0].desc.generation not in live, "bad input quarantined"
    qdir = os.path.join(cfs.directory, "quarantine")
    assert os.path.isdir(qdir) and os.listdir(qdir)


# ---------------------------------------------------------- hot-resize --

def test_hot_resize_mid_compaction(tmp_path):
    """Growing and shrinking the pool WHILE a compaction drains through
    it must neither wedge nor change the output bytes."""
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _table()

    def build(tag):
        cfs = ColumnFamilyStore(table, str(tmp_path / tag),
                                commitlog=None)
        for gen in (1, 2, 3):
            w = SSTableWriter(Descriptor(cfs.directory, gen), table)
            w.append(_mixed_batch(table, seed=gen, n=40_000))
            w.finish()
        cfs.reload_sstables()
        return cfs

    def digests(cfs):
        out = {}
        for s in cfs.live_sstables():
            with open(s.desc.path("Digest.crc32")) as f:
                out[s.n_cells] = f.read().strip()
        return out

    ref_cfs = build("ref")
    CompactionTask(ref_cfs, ref_cfs.tracker.view(), compress_pool=0,
                   round_cells=8192).execute()
    ref = digests(ref_cfs)

    cfs = build("resized")
    pool = CompressorPool(1)
    task = CompactionTask(cfs, cfs.tracker.view(), compress_pool=pool,
                          round_cells=8192)
    err = []

    def run():
        try:
            task.execute()
        except BaseException as e:   # pragma: no cover - fails the test
            err.append(e)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 30.0
    sizes = [4, 2, 6, 1]
    while t.is_alive() and time.monotonic() < deadline:
        if sizes:
            pool.set_workers(sizes.pop(0))
        time.sleep(0.01)
    t.join(timeout=60.0)
    pool.shutdown(timeout=5.0)
    assert not t.is_alive(), "compaction wedged during pool resize"
    assert not err, err
    assert digests(cfs) == ref


def test_settings_knob_resizes_global_pool(tmp_path):
    """compaction_compressor_threads hot-applies to the shared pool via
    the engine's settings listener (0 = auto)."""
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine

    schema = Schema()
    schema.create_keyspace("pk")
    schema.add_table(_table("knob"))
    eng = StorageEngine(str(tmp_path / "data"), schema,
                        durable_writes=False)
    try:
        pool = get_pool()
        eng.settings.set("compaction_compressor_threads", 3)
        assert pool.workers == 3
        eng.settings.set("compaction_compressor_threads", 0)
        assert pool.workers == auto_workers()
    finally:
        eng.close()


def test_pool_shutdown_completes_queued_jobs():
    """shutdown() must never strand a queued job: a stranded pack job
    would park its writer's ordered completion thread on ready.wait()
    forever. Never-started jobs run inline on the shutdown caller."""
    pool = CompressorPool(1)
    gate = threading.Event()
    started = threading.Event()
    ran = []

    def job1():
        started.set()
        gate.wait(10.0)

    pool.submit(job1)
    assert started.wait(5.0), "worker never picked up job 1"
    pool.submit(lambda: ran.append(1))   # queued behind the busy worker
    t = threading.Thread(target=lambda: pool.shutdown(timeout=10.0))
    t.start()
    deadline = time.monotonic() + 5.0
    while not ran and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ran, "queued job stranded by shutdown"
    gate.set()
    t.join(timeout=15.0)
    assert not t.is_alive()


# -------------------------------------------- decode-ahead + drive-bys --

def test_decode_ahead_outputs_identical(tmp_path):
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _table()

    def leg(tag, da):
        cfs = ColumnFamilyStore(table, str(tmp_path / tag),
                                commitlog=None)
        for gen in (1, 2):
            # small input segments so rounds have something to prefetch
            w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                              segment_cells=4096)
            w.append(_mixed_batch(table, seed=gen, n=40_000))
            w.finish()
        cfs.reload_sstables()
        task = CompactionTask(cfs, cfs.tracker.view(), compress_pool=0,
                              decode_ahead=da, round_cells=8192)
        task.execute()
        [s] = cfs.live_sstables()
        with open(s.desc.path("Digest.crc32")) as f:
            return f.read().strip(), task.profile

    ref, _ = leg("noda", False)
    got, prof = leg("da", True)
    assert got == ref
    assert "decode_ahead" in prof, "prefetch thread never decoded"


def test_data_offset_published(tmp_path):
    """The cross-thread roll-check surface: equals the final Data.db
    payload size after finish, and trails appends monotonically."""
    table = _table()
    batch = _mixed_batch(table, n=20_000)
    pool = CompressorPool(2)
    d = str(tmp_path / "off")
    w = SSTableWriter(Descriptor(d, 1), table, segment_cells=2048,
                      compress_pool=pool)
    try:
        seen = [0]
        for i in range(0, len(batch), 2048):
            w.append(batch.slice_range(i, min(i + 2048, len(batch))))
            off = w.data_offset()
            assert off >= seen[0], "published offset went backwards"
            seen[0] = off
        w.finish()
    finally:
        pool.shutdown(timeout=5.0)
    assert w.data_offset() == w._data_off > 0


def test_compress_metrics_move(tmp_path):
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.service.metrics import GLOBAL
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _table()
    cfs = ColumnFamilyStore(table, str(tmp_path / "m"), commitlog=None)
    for gen in (1, 2):
        w = SSTableWriter(Descriptor(cfs.directory, gen), table)
        w.append(_mixed_batch(table, seed=gen, n=20_000))
        w.finish()
    cfs.reload_sstables()
    before = GLOBAL.counter("compaction.compress_segments")
    pool = CompressorPool(2)
    try:
        CompactionTask(cfs, cfs.tracker.view(), compress_pool=pool,
                       round_cells=8192).execute()
    finally:
        pool.shutdown(timeout=5.0)
    assert GLOBAL.counter("compaction.compress_segments") > before


def test_fallback_compress_iov_zero_copy_equivalent():
    """The generic compress_iov must accept numpy/memoryview frames
    without bytes() staging and round-trip identically."""
    from cassandra_tpu.ops.codec import Compressor, get_compressor

    rng = np.random.default_rng(3)
    frames = [rng.integers(97, 122, 4096, dtype=np.uint8),
              rng.integers(0, 256, 1000, dtype=np.uint8),
              np.zeros(0, dtype=np.uint8)]
    lz4 = get_compressor("LZ4Compressor")
    dst, offs, sizes = Compressor.compress_iov(lz4, frames)
    for i, f in enumerate(frames):
        c = bytes(dst[int(offs[i]):int(offs[i]) + int(sizes[i])])
        assert lz4.uncompress(c, f.nbytes) == f.tobytes()


# -------------------------------------------------------------- sim --

def test_parallel_compress_deterministic_under_sim(tmp_path):
    """Same seed, pool-compressed compaction under the sim scheduler:
    identical sstable digests across runs — worker scheduling cannot
    leak into bytes (the property that keeps the write leg simulable)."""
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.sim.scheduler import simulated
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _table()

    def run(tag):
        with simulated(99):
            cfs = ColumnFamilyStore(table, str(tmp_path / tag),
                                    commitlog=None)
            for gen in (1, 2):
                w = SSTableWriter(Descriptor(cfs.directory, gen), table)
                w.append(_mixed_batch(table, seed=gen, n=30_000))
                w.finish()
            cfs.reload_sstables()
            pool = CompressorPool(3)
            try:
                CompactionTask(cfs, cfs.tracker.view(),
                               compress_pool=pool,
                               round_cells=8192).execute()
            finally:
                pool.shutdown(timeout=5.0)
            [s] = cfs.live_sstables()
            with open(s.desc.path("Digest.crc32")) as f:
                return f.read().strip()

    assert run("a") == run("b")


# ------------------------------------------------------- A/B harness --

@pytest.mark.slow
def test_compaction_ab_harness(tmp_path):
    """Full tier-2 drill: scripts/check_compaction_ab.py — serial vs
    threaded vs pool-1 vs pool-4 compaction and serial vs pooled flush,
    sha256 component identity + merged-view digests."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_compaction_ab",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts",
            "check_compaction_ab.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    diverged = mod.run_check(str(tmp_path))
    assert diverged == [], "\n".join(diverged)
