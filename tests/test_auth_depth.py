"""Auth depth (round 3): CIDR authorization, network (datacenter)
authorization, mTLS identity mapping, auth caches, ALTER ROLE / ADD
IDENTITY CQL — auth/CIDRPermissionsManager, CassandraNetworkAuthorizer,
MutualTlsAuthenticator, AuthCache counterparts."""
import time

import pytest

from cassandra_tpu.service.auth import (AuthCache, AuthenticationError,
                                        AuthService, UnauthorizedError)


@pytest.fixture
def auth(tmp_path):
    return AuthService(str(tmp_path), enabled=True)


def test_cidr_groups(auth):
    auth.create_role("app", "pw")
    auth.set_cidr_group("office", ["10.1.0.0/16", "192.168.7.0/24"])
    auth.alter_role_access("app", cidr_groups=["office"])
    auth.check_cidr("app", "10.1.2.3")          # inside
    auth.check_cidr("app", "192.168.7.200")     # inside
    with pytest.raises(UnauthorizedError, match="may not connect"):
        auth.check_cidr("app", "172.16.0.1")    # outside
    # superusers and unrestricted roles connect from anywhere
    auth.check_cidr("cassandra", "8.8.8.8")
    auth.create_role("free", "pw")
    auth.check_cidr("free", "8.8.8.8")
    # unknown group rejected at grant time
    with pytest.raises(ValueError, match="unknown CIDR groups"):
        auth.alter_role_access("app", cidr_groups=["nope"])
    # clearing restores access
    auth.alter_role_access("app", cidr_groups=[])
    auth.check_cidr("app", "172.16.0.1")


def test_network_authorization(auth):
    auth.create_role("dc1only", "pw")
    auth.alter_role_access("dc1only", datacenters=["dc1"])
    auth.check_datacenter("dc1only", "dc1")
    with pytest.raises(UnauthorizedError, match="no access to datacenter"):
        auth.check_datacenter("dc1only", "dc2")
    auth.check_datacenter("cassandra", "dc2")   # superuser unrestricted
    auth.alter_role_access("dc1only", datacenters=[])   # ALL DATACENTERS
    auth.check_datacenter("dc1only", "dc2")


def test_mtls_identities(auth):
    auth.create_role("svc", None, login=True)
    auth.add_identity("spiffe://cluster/ns/prod/svc", "svc")
    assert auth.authenticate_identity(
        "spiffe://cluster/ns/prod/svc") == "svc"
    with pytest.raises(AuthenticationError, match="no role"):
        auth.authenticate_identity("spiffe://evil")
    auth.drop_identity("spiffe://cluster/ns/prod/svc")
    with pytest.raises(AuthenticationError):
        auth.authenticate_identity("spiffe://cluster/ns/prod/svc")
    with pytest.raises(ValueError, match="unknown role"):
        auth.add_identity("x", "ghost")


def test_auth_cache_memoizes_and_invalidates(auth):
    auth.create_role("u", "pw")
    auth.grant("SELECT", "ks", "u")
    auth.check("u", "SELECT", "ks")
    # revoke invalidates the verdict cache immediately (persisted save
    # path calls invalidate_all), so the next check fails
    auth.revoke("SELECT", "ks", "u")
    with pytest.raises(UnauthorizedError):
        auth.check("u", "SELECT", "ks")


def test_auth_cache_ttl():
    c = AuthCache(validity=0.05)
    calls = []
    assert c.get("k", lambda: calls.append(1) or "v") == "v"
    assert c.get("k", lambda: calls.append(1) or "v") == "v"
    assert len(calls) == 1          # cached
    time.sleep(0.06)
    assert c.get("k", lambda: calls.append(1) or "v") == "v"
    assert len(calls) == 2          # expired, re-loaded


def test_persistence_roundtrip(tmp_path):
    a = AuthService(str(tmp_path), enabled=True)
    a.create_role("app", "pw")
    a.set_cidr_group("office", ["10.0.0.0/8"])
    a.alter_role_access("app", cidr_groups=["office"],
                        datacenters=["dc2"])
    a.add_identity("CN=app", "app")
    b = AuthService(str(tmp_path), enabled=True)
    assert b.cidr_groups == {"office": ["10.0.0.0/8"]}
    assert b.authenticate_identity("CN=app") == "app"
    with pytest.raises(UnauthorizedError):
        b.check_cidr("app", "11.0.0.1")
    with pytest.raises(UnauthorizedError):
        b.check_datacenter("app", "dc1")


def test_cql_role_access_and_identity(tmp_path):
    """CREATE/ALTER ROLE ... WITH ACCESS TO DATACENTERS / FROM CIDRS and
    ADD/DROP IDENTITY through the full CQL path."""
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine

    eng = StorageEngine(str(tmp_path), Schema(), durable_writes=False,
                        auth_enabled=True)
    try:
        from cassandra_tpu.cql.processor import QueryProcessor
        qp = QueryProcessor(eng)

        def ex(q):
            return qp.process(q, user="cassandra")

        eng.auth.set_cidr_group("office", ["10.0.0.0/8"])
        ex("CREATE ROLE app WITH password = 'pw' AND "
           "ACCESS TO DATACENTERS {'dc1', 'dc3'}")
        assert eng.auth.roles["app"]["datacenters"] == ["dc1", "dc3"]
        ex("ALTER ROLE app WITH ACCESS FROM CIDRS {'office'}")
        assert eng.auth.roles["app"]["cidr_groups"] == ["office"]
        ex("ALTER ROLE app WITH superuser = true")
        assert eng.auth.roles["app"]["superuser"] is True
        ex("ALTER ROLE app WITH ACCESS TO ALL DATACENTERS")
        assert eng.auth.roles["app"]["datacenters"] == []
        ex("ADD IDENTITY 'spiffe://c/app' TO ROLE 'app'")
        assert eng.auth.authenticate_identity("spiffe://c/app") == "app"
        ex("DROP IDENTITY 'spiffe://c/app'")
        with pytest.raises(AuthenticationError):
            eng.auth.authenticate_identity("spiffe://c/app")
        # non-superusers cannot manage roles/identities
        ex("CREATE ROLE pleb WITH password = 'x'")
        with pytest.raises(Exception, match="superuser"):
            qp.process("ADD IDENTITY 'i' TO ROLE 'app'", user="pleb")
    finally:
        eng.close()


def _mtls_certs(d):
    """CA + server cert + client cert with CN=svc-client (the mTLS
    identity)."""
    import subprocess

    d = str(d)

    def run(*args):
        subprocess.run(["openssl", *args], cwd=d, check=True,
                       capture_output=True)

    run("req", "-x509", "-newkey", "rsa:2048", "-days", "1", "-nodes",
        "-keyout", "ca.key", "-out", "ca.crt", "-subj", "/CN=ctpu-ca")
    for name, cn in (("server", "127.0.0.1"), ("client", "svc-client")):
        run("req", "-newkey", "rsa:2048", "-nodes", "-keyout",
            f"{name}.key", "-out", f"{name}.csr", "-subj", f"/CN={cn}")
        run("x509", "-req", "-in", f"{name}.csr", "-CA", "ca.crt",
            "-CAkey", "ca.key", "-CAcreateserial", "-days", "1",
            "-out", f"{name}.crt")
    return d


def test_mtls_connect_end_to_end(tmp_path):
    """A client certificate identity authenticates over a real TLS
    native-protocol connection with no password exchange."""
    import shutil

    if shutil.which("openssl") is None:
        pytest.skip("openssl unavailable")
    from cassandra_tpu.client import Cluster
    from cassandra_tpu.cluster.tls import TLSConfig
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.transport_server import CQLServer

    d = _mtls_certs(tmp_path)
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        durable_writes=False, auth_enabled=True)
    srv = None
    try:
        eng.auth.create_role("clientrole", None)
        eng.auth.grant("SELECT", "ALL KEYSPACES", "clientrole")
        eng.auth.add_identity("svc-client", "clientrole")
        srv = CQLServer(eng, tls=TLSConfig(
            f"{d}/server.crt", f"{d}/server.key", f"{d}/ca.crt",
            require_client_auth=True))
        sess = Cluster("127.0.0.1", srv.port, tls=True,
                       cafile=f"{d}/ca.crt",
                       certfile=f"{d}/client.crt",
                       keyfile=f"{d}/client.key").connect()
        rows = sess.execute("SELECT * FROM system.local")
        assert rows.rows
    finally:
        if srv is not None:
            srv.close()
        eng.close()
