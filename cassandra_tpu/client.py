"""Minimal native-protocol client driver.

Reference counterpart: the DataStax python-driver's Cluster/Session
surface (the reference ships no in-tree driver; this one exists so the
framework is drivable over the WIRE without any external dependency, and
doubles as the conformance test harness for transport_server.py).

    from cassandra_tpu.client import Cluster
    session = Cluster("127.0.0.1", 9042).connect()
    session.execute("USE ks")
    rows = session.execute("SELECT ... WHERE k = ?", [b"..."]).rows

Bound values are sent in wire encoding: pass `bytes` you serialized with
the column's CQL type, or let `serialize_params` do it from a schema
table. Paging: pass fetch_size / paging_state like the server-side
Session.
"""
from __future__ import annotations

import socket
import struct
import threading

from . import transport_server as ts


class DriverError(Exception):
    pass


class Rows:
    def __init__(self, column_names, rows, paging_state=None):
        self.column_names = column_names
        self.rows = rows
        self.paging_state = paging_state

    def __iter__(self):
        return iter(self.rows)


_DECODERS = {
    0x02: lambda b: struct.unpack(">q", b)[0],
    0x03: lambda b: b,
    0x04: lambda b: b != b"\x00",
    0x07: lambda b: struct.unpack(">d", b)[0],
    0x0B: lambda b: struct.unpack(">q", b)[0],
    0x0C: lambda b: __import__("uuid").UUID(bytes=b),
    0x0D: lambda b: b.decode(),
}


class ClientSession:
    def __init__(self, host: str, port: int, user: str | None = None,
                 password: str | None = None, tls: bool = False,
                 cafile: str | None = None, certfile: str | None = None,
                 keyfile: str | None = None):
        """tls=True (or any of cafile/certfile) speaks TLS: the server
        is verified against `cafile` when given, and `certfile`/
        `keyfile` are presented when the server demands client certs."""
        self._sock = socket.create_connection((host, port), timeout=10.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if tls or cafile or certfile:
            from .cluster.tls import client_side_context
            self._sock = client_side_context(
                cafile, certfile, keyfile).wrap_socket(self._sock)
        self._stream = 0
        self._lock = threading.Lock()
        op, body = self._request(ts.OP_STARTUP,
                                 struct.pack(">H", 1)
                                 + ts._string("CQL_VERSION")
                                 + ts._string("3.4.5"))
        if op == ts.OP_AUTHENTICATE:
            token = b"\x00" + (user or "").encode() + b"\x00" \
                + (password or "").encode()
            op, body = self._request(ts.OP_AUTH_RESPONSE, ts._bytes(token))
            if op != ts.OP_AUTH_SUCCESS:
                raise DriverError("authentication failed")
        elif op != ts.OP_READY:
            raise DriverError(f"unexpected startup response {op}")

    # ------------------------------------------------------------- frames

    def _request(self, opcode: int, body: bytes):
        with self._lock:
            self._stream = (self._stream + 1) % 32768
            stream = self._stream
            self._sock.sendall(struct.pack(
                ">BBhBI", ts.VERSION_REQ, 0, stream, opcode, len(body))
                + body)
            hdr = self._read_exact(9)
            _ver, _flags, rstream, op = struct.unpack(">BBhB", hdr[:5])
            (length,) = struct.unpack(">I", hdr[5:9])
            rbody = self._read_exact(length) if length else b""
            if rstream != stream:
                raise DriverError("stream mismatch")
            return op, rbody

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise DriverError("connection closed")
            buf += chunk
        return bytes(buf)

    # -------------------------------------------------------------- query

    def execute(self, query: str, params: list[bytes | None] | None = None,
                fetch_size: int | None = None,
                paging_state: bytes | None = None) -> Rows:
        body = bytearray()
        body += ts._long_string(query)
        body += struct.pack(">H", 1)        # consistency ONE (server CL
                                            # policy governs for now)
        flags = 0
        if params:
            flags |= 0x01
        if fetch_size is not None:
            flags |= 0x04
        if paging_state is not None:
            flags |= 0x08
        body.append(flags)
        if params:
            body += struct.pack(">H", len(params))
            for p in params:
                body += ts._bytes(p)
        if fetch_size is not None:
            body += struct.pack(">i", fetch_size)
        if paging_state is not None:
            body += ts._bytes(paging_state)
        op, rbody = self._request(ts.OP_QUERY, bytes(body))
        return self._decode_result(op, rbody)

    def _decode_result(self, op: int, body: bytes) -> Rows:
        if op == ts.OP_ERROR:
            (code,) = struct.unpack_from(">i", body, 0)
            msg, _ = ts._read_string(body, 4)
            raise DriverError(f"[{code:#06x}] {msg}")
        if op != ts.OP_RESULT:
            raise DriverError(f"unexpected opcode {op}")
        (kind,) = struct.unpack_from(">i", body, 0)
        pos = 4
        if kind in (ts.RESULT_VOID, ts.RESULT_SCHEMA_CHANGE):
            return Rows([], [])
        if kind == ts.RESULT_SET_KEYSPACE:
            ks, _ = ts._read_string(body, pos)
            return Rows([], [])
        if kind != ts.RESULT_ROWS:
            raise DriverError(f"unsupported result kind {kind}")
        (flags,) = struct.unpack_from(">I", body, pos)
        pos += 4
        (ncols,) = struct.unpack_from(">i", body, pos)
        pos += 4
        paging = None
        if flags & 0x0002:
            paging, pos = ts._read_bytes(body, pos)
        if flags & 0x0001:
            _, pos = ts._read_string(body, pos)
            _, pos = ts._read_string(body, pos)
        names = []
        tids = []
        for _ in range(ncols):
            name, pos = ts._read_string(body, pos)
            (tid,) = struct.unpack_from(">H", body, pos)
            pos += 2
            names.append(name)
            tids.append(tid)
        (nrows,) = struct.unpack_from(">i", body, pos)
        pos += 4
        rows = []
        for _ in range(nrows):
            row = []
            for tid in tids:
                b, pos = ts._read_bytes(body, pos)
                if b is None:
                    row.append(None)
                else:
                    row.append(_DECODERS.get(tid, lambda x: x)(b))
            rows.append(tuple(row))
        return Rows(names, rows, paging)

    def prepare(self, query: str) -> bytes:
        op, body = self._request(ts.OP_PREPARE, ts._long_string(query))
        if op == ts.OP_ERROR:
            (code,) = struct.unpack_from(">i", body, 0)
            msg, _ = ts._read_string(body, 4)
            raise DriverError(f"[{code:#06x}] {msg}")
        (kind,) = struct.unpack_from(">i", body, 0)
        if kind != ts.RESULT_PREPARED:
            raise DriverError(f"unexpected result kind {kind}")
        (n,) = struct.unpack_from(">H", body, 4)
        return bytes(body[6:6 + n])

    def execute_prepared(self, qid: bytes,
                         params: list[bytes | None] | None = None,
                         fetch_size: int | None = None,
                         paging_state: bytes | None = None) -> Rows:
        body = bytearray()
        body += struct.pack(">H", len(qid)) + qid
        body += struct.pack(">H", 1)
        flags = 0
        if params:
            flags |= 0x01
        if fetch_size is not None:
            flags |= 0x04
        if paging_state is not None:
            flags |= 0x08
        body.append(flags)
        if params:
            body += struct.pack(">H", len(params))
            for p in params:
                body += ts._bytes(p)
        if fetch_size is not None:
            body += struct.pack(">i", fetch_size)
        if paging_state is not None:
            body += ts._bytes(paging_state)
        op, rbody = self._request(ts.OP_EXECUTE, bytes(body))
        return self._decode_result(op, rbody)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class Cluster:
    def __init__(self, host: str = "127.0.0.1", port: int = 9042,
                 user: str | None = None, password: str | None = None,
                 tls: bool = False, cafile: str | None = None,
                 certfile: str | None = None, keyfile: str | None = None):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.tls, self.cafile = tls, cafile
        self.certfile, self.keyfile = certfile, keyfile

    def connect(self) -> ClientSession:
        return ClientSession(self.host, self.port, self.user,
                             self.password, tls=self.tls,
                             cafile=self.cafile, certfile=self.certfile,
                             keyfile=self.keyfile)


def serialize_params(table, columns: list[str], values: list) -> list:
    """Wire-encode bind values using a schema table's column types."""
    out = []
    for c, v in zip(columns, values):
        out.append(None if v is None
                   else table.columns[c].cql_type.serialize(v))
    return out
