"""Adaptive compaction controller: observe → decide → actuate.

Reference counterpart: none in-tree — the reference leaves strategy
choice to the operator. The LSM design-space survey (arXiv 2202.04522)
and the Bigtable merge analysis (arXiv 1407.3008) both treat compaction
trigger, layout and granularity as tunable axes whose optimum shifts
with the read/write/space mix; this loop moves the node along those
axes as the observed mix shifts, so a phase-shifting workload is not
stuck with whichever static strategy the table was created with.

`AdaptiveCompactionController` (engine-scoped, the MetricsHistoryService
shape):

- A fixed-interval decision loop with an injectable clock. Each
  `tick()` reads the SAME per-table counters the metrics-history rings
  retain (window deltas of writes/reads), the derived amplification
  gauges (`ColumnFamilyStore.amplification()`), and the recent
  sstables' tombstone mix, classifies each table's recent window into
  the saturation matrix's workload regimes — write-burst / read-heavy /
  time-series / space-pressured — and picks compaction strategy +
  parameters (STCS↔LCS↔TWCS, thresholds, output sizing) plus the
  engine-level throughput / mesh / compressor-pool posture.
- **Actuation only through existing seams**: per-table strategy changes
  swap `table.params.compaction` through
  `ColumnFamilyStore.set_compaction_params` (the `get_strategy`
  re-selection seam — in-flight tasks are protected by the manager's
  claim registry and finish under their OLD plan); engine knobs go
  through `Settings.set(..., source="controller")`, so every decision
  is a `controller.decision` / `config.reload` diagnostic event in the
  flight recorder.
- **Hysteresis + cooldown**: a candidate regime must persist
  `adaptive_compaction_confirm_ticks` consecutive ticks before it
  actuates, and an applied strategy change starts a per-table
  `adaptive_compaction_cooldown` window inside which no further change
  lands — no A→B→A flapping on a noisy boundary.
- **Freeze**: `freeze()` keeps the loop ticking but applies nothing;
  the frozen flag persists as a marker file under the engine's data
  dir, so it survives loop AND engine restarts (an operator's "stop
  touching my cluster" outlives the process).
- **Zero-cost when off** (the diagnostic-bus rule): while the mutable
  `adaptive_compaction_enabled` knob is false no decision thread
  exists and nothing is classified; `tick()` stays callable on demand
  (tests, `scripts/check_controller.py`, the bench's deterministic
  cadence). The knob is ENGINE-scoped like `metrics_history_enabled`.

Surfaces: `system_views.controller_decisions`, `nodetool
autocompaction [status|history|freeze|unfreeze]`, the `controller.*`
metrics (docs/observability.md), the `controller_decisions` section of
every flight-recorder bundle, and bench.py's `adaptive` section.
"""
from __future__ import annotations

import os
import threading
import time

# ctpulint: clock-injectable
# every timestamp and duration in this module comes from the
# controller's injected clock; `time.monotonic` / `time.time` appear
# only as production defaults (references, never direct calls)

from collections import deque

from ..service.metrics import GLOBAL as METRICS

FROZEN_MARKER = "controller.frozen"

# the decision table: regime -> compaction params (class + thresholds +
# output sizing), docs/adaptive-compaction.md. Values are COMPLETE
# replacement param dicts — the actuation seam swaps atomically, never
# merges, so a decision is exactly reproducible from its ledger entry.
REGIME_PARAMS = {
    # bursty ingest: size-tiered merging amortizes best; reference
    # min_threshold keeps write amplification low under churn
    "write_burst": {"class": "SizeTieredCompactionStrategy",
                    "min_threshold": 4},
    # read-dominated: leveling bounds sstables-per-read; the size
    # target carries into CompactionTask.max_output_bytes
    "read_heavy": {"class": "LeveledCompactionStrategy",
                   "sstable_size_in_mb": 160, "l0_threshold": 4},
    # append-mostly with expiring data: time windows make whole-sstable
    # expiry a rewrite-free DROP
    "time_series": {"class": "TimeWindowCompactionStrategy",
                    "compaction_window_unit": "HOURS",
                    "compaction_window_size": 1},
    # live size far above logical: eager size-tiering (threshold 2)
    # reclaims overlap fastest
    "space_pressured": {"class": "SizeTieredCompactionStrategy",
                        "min_threshold": 2},
}

# regimes whose backlog wants the write path wide open: the engine
# posture unthrottles compaction and widens the mesh/compressor pools
# while any table sits in one of these
BOOST_REGIMES = ("write_burst", "space_pressured")

# engine-posture knob values while boosting (0.0 rate = unthrottled;
# pool widths are modest fixed widths — the pools are shared process
# state and output bytes are width-invariant)
BOOST_KNOBS = {"compaction_throughput_mib_per_sec": 0.0,
               "compaction_mesh_devices": 2,
               "compaction_compressor_threads": 2}


class AdaptiveCompactionController:
    """Engine-scoped adaptive compaction controller (see module
    docstring). All decision state is guarded by one lock; observation
    reads live store surfaces outside it."""

    MIN_INTERVAL_S = 0.05    # same floor rule as MetricsHistoryService:
    #                          a 0-second knob must not boot a busy-spin
    #                          decision thread
    LEDGER_CAPACITY = 256    # bounded decision ring (newest kept)

    # classification thresholds (docs/adaptive-compaction.md): window
    # deltas below MIN_ACTIVITY are idle noise, not a regime
    MIN_ACTIVITY = 16
    READ_WRITE_RATIO = 2.0       # reads >= ratio * writes -> read_heavy
    TOMBSTONE_FRACTION = 0.20    # recent-sstable tombstone share ->
    #                              time_series
    SPACE_AMP_LIMIT = 2.0        # live/logical partition ratio ->
    #                              space_pressured

    def __init__(self, engine=None, clock=time.monotonic,
                 interval_s: float = 30.0, wall_clock=time.time):
        self.engine = engine
        self.clock = clock
        # wall-clock reference for rendering surfaces only (ledger
        # at_ms must join against diagnostic-event timestamps);
        # cooldown/hysteresis arithmetic stays on the injectable
        # monotonic clock
        self.wall_clock = wall_clock
        self.interval_s = max(float(interval_s), self.MIN_INTERVAL_S)
        self._lock = threading.Lock()
        # per-table hysteresis state: table_id -> {regime, candidate,
        # streak, last_change (controller clock), prev counter snapshot,
        # generation watermark bounding the "recent window" sstables}
        self._state: dict = {}
        self._ledger: deque = deque(maxlen=self.LEDGER_CAPACITY)
        self._seq = 0
        # engine-posture memory: knob values saved when boost engaged,
        # restored verbatim on disengage (never clobber an operator's
        # setting with a hardcoded default)
        self._boost_saved: dict | None = None
        self.ticks = 0
        self.decisions_applied = 0
        self.decisions_skipped = 0
        self._frozen = self._load_frozen()
        self._stop: threading.Event | None = None
        self._wake: threading.Event | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ config --

    @property
    def enabled(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def set_enabled(self, on) -> None:
        """The `adaptive_compaction_enabled` knob landing: start or
        stop the decision thread. Ledger and hysteresis state survive a
        disable — history up to the stop stays queryable."""
        if on:
            self.start()
        else:
            self.stop()

    def set_interval(self, seconds: float) -> None:
        """The `adaptive_compaction_interval` knob: a parked loop is
        woken so the new period applies NOW."""
        self.interval_s = max(float(seconds), self.MIN_INTERVAL_S)
        wake = self._wake
        if wake is not None:
            wake.set()

    # ------------------------------------------------------------ freeze --

    def _marker_path(self) -> str | None:
        eng = self.engine
        data_dir = getattr(eng, "data_dir", None) if eng else None
        if not data_dir:
            return None
        return os.path.join(data_dir, FROZEN_MARKER)

    def _load_frozen(self) -> bool:
        p = self._marker_path()
        return bool(p and os.path.exists(p))

    @property
    def frozen(self) -> bool:
        return self._frozen

    def freeze(self) -> None:
        """nodetool autocompaction freeze: the loop keeps ticking (and
        counting) but applies NOTHING. Persisted as a data-dir marker
        so an engine restart comes back frozen."""
        self._frozen = True
        p = self._marker_path()
        if p:
            with open(p, "w") as f:
                f.write("frozen\n")
        from ..service import diagnostics
        diagnostics.publish("controller.freeze", frozen=True)

    def unfreeze(self) -> None:
        self._frozen = False
        p = self._marker_path()
        if p and os.path.exists(p):
            os.remove(p)
        from ..service import diagnostics
        diagnostics.publish("controller.freeze", frozen=False)

    # -------------------------------------------------------------- loop --

    def start(self) -> None:
        """Idempotent decision-loop start (daemon thread, the
        metrics-history sampler shape)."""
        if self.enabled:
            return
        stop = threading.Event()
        wake = threading.Event()
        self._stop = stop
        self._wake = wake

        def _run():
            while not stop.is_set():
                try:
                    if wake.wait(self.interval_s):
                        wake.clear()   # interval kick: re-read the
                        continue       # new period, no tick yet
                    self.tick()
                except Exception:
                    pass   # a broken gauge must not kill the loop

        self._thread = threading.Thread(target=_run,
                                        name="adaptive-compaction",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._wake is not None:
            self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._thread = None
        self._stop = None
        self._wake = None

    close = stop

    # ------------------------------------------------------------ observe --

    def _signals(self, cfs, st: dict) -> dict:
        """One table's recent-window signals: counter deltas since the
        last tick (the same per-table counters the metrics-history
        rings retain — same-source with every other surface), the
        derived amplification gauges, and the tombstone mix of sstables
        flushed since the last tick (the generation watermark bounds
        the 'recent window')."""
        m = cfs.metrics
        prev = st.get("snap") or {"writes": 0, "reads": 0}
        writes_d = m.get("writes", 0) - prev["writes"]
        reads_d = m.get("reads", 0) - prev["reads"]
        live = cfs.live_sstables()
        watermark = st.get("gen_watermark", 0)
        recent = [s for s in live if s.desc.generation > watermark]
        tomb = sum(s.n_tombstones for s in recent)
        cells = sum(s.n_cells for s in recent)
        amp = cfs.amplification()
        sig = {
            "writes_delta": writes_d,
            "reads_delta": reads_d,
            "recent_sstables": len(recent),
            "tombstone_fraction": (tomb / cells) if cells else 0.0,
            "write_amplification": amp["write_amplification"],
            "space_amplification": amp["space_amplification"],
        }
        # retained-history rates when the sampler is on: the long-window
        # corroboration of the tick-window deltas (status surface; the
        # rings and the deltas read the SAME counters)
        hist = getattr(self.engine, "metrics_history", None)
        if hist is not None:
            base = f"table.{cfs.table.keyspace}.{cfs.table.name}"
            rate = hist.rate(f"{base}.writes", limit=1)
            if rate:
                sig["write_rate_per_s"] = round(rate[-1]["per_s"], 3)
        st["snap"] = {"writes": m.get("writes", 0),
                      "reads": m.get("reads", 0)}
        st["gen_watermark"] = max(
            [s.desc.generation for s in live], default=watermark)
        return sig

    def _classify(self, sig: dict) -> str | None:
        """Signals -> regime (None = idle window, no opinion). Order
        matters: expiry mix trumps volume, read dominance trumps the
        space check, space pressure trumps plain write burst."""
        active = max(sig["writes_delta"], sig["reads_delta"]) \
            >= self.MIN_ACTIVITY
        if not active:
            return None
        if sig["writes_delta"] >= self.MIN_ACTIVITY \
                and sig["recent_sstables"] > 0 \
                and sig["tombstone_fraction"] >= self.TOMBSTONE_FRACTION:
            return "time_series"
        if sig["reads_delta"] >= self.MIN_ACTIVITY \
                and sig["reads_delta"] >= self.READ_WRITE_RATIO \
                * max(sig["writes_delta"], 1):
            return "read_heavy"
        if sig["writes_delta"] >= self.MIN_ACTIVITY \
                and sig["space_amplification"] >= self.SPACE_AMP_LIMIT:
            return "space_pressured"
        if sig["writes_delta"] >= self.MIN_ACTIVITY:
            return "write_burst"
        return None

    # ------------------------------------------------------------- decide --

    def tick(self) -> int:
        """One decision pass NOW (on-demand callers — tests, the bench's
        deterministic cadence, check_controller — need no running
        thread). Returns decisions APPLIED this tick."""
        METRICS.incr("controller.ticks")
        eng = self.engine
        applied = 0
        with self._lock:
            self.ticks += 1
        if eng is None:
            return 0
        settings = eng.settings
        now = self.clock()
        cooldown = float(settings.get("adaptive_compaction_cooldown"))
        confirm = max(
            int(settings.get("adaptive_compaction_confirm_ticks")), 1)
        regimes: set = set()
        for cfs in list(eng.stores.values()):
            with self._lock:
                st = self._state.setdefault(
                    cfs.table.id,
                    {"regime": None, "candidate": None, "streak": 0,
                     "last_change": None, "snap": None,
                     "gen_watermark": 0, "table":
                     f"{cfs.table.keyspace}.{cfs.table.name}"})
            try:
                sig = self._signals(cfs, st)
            except Exception:
                continue   # a store mid-drop must not kill the pass
            regime = self._classify(sig)
            st["signals"] = sig
            if st["regime"] is not None:
                regimes.add(st["regime"])
            if regime is None or regime == st["regime"]:
                st["candidate"], st["streak"] = None, 0
                continue
            if regime == st["candidate"]:
                st["streak"] += 1
            else:
                st["candidate"], st["streak"] = regime, 1
            if st["streak"] < confirm:
                self._skip()   # hysteresis: unconfirmed candidate
                continue
            if st["last_change"] is not None \
                    and now - st["last_change"] < cooldown:
                self._skip(cfs, regime, "cooldown")
                continue
            if self._frozen:
                self._skip(cfs, regime, "frozen")
                continue
            applied += self._apply_strategy(cfs, st, regime, now)
            regimes.add(regime)
        if not self._frozen:
            applied += self._apply_posture(settings, regimes)
        return applied

    # ------------------------------------------------------------ actuate --

    def _apply_strategy(self, cfs, st: dict, regime: str,
                        now: float) -> int:
        """Confirmed regime change for one table: atomic params swap
        through the ColumnFamilyStore seam (in-flight tasks keep their
        claimed inputs and finish under the OLD plan), ledger + event +
        metric, hysteresis state reset, cooldown armed."""
        new = dict(REGIME_PARAMS[regime])
        old = dict(cfs.table.params.compaction)
        st.update(regime=regime, candidate=None, streak=0,
                  last_change=now)
        if old == new:
            return 0   # regime label changed, params already right
        cfs.set_compaction_params(new)
        self._record(
            keyspace=cfs.table.keyspace, table=cfs.table.name,
            regime=regime, action="strategy",
            old=old.get("class", "SizeTieredCompactionStrategy"),
            new=new["class"], applied=True, reason="confirmed")
        return 1

    def _apply_posture(self, settings, regimes: set) -> int:
        """Engine-level posture: while any table sits in a
        backlog-heavy regime, unthrottle compaction and widen the
        mesh/compressor pools — through Settings.set with
        source=\"controller\", so each change is an attributed
        config.reload event. Disengaging restores the exact values the
        operator had."""
        boost = bool(regimes & set(BOOST_REGIMES))
        n = 0
        if boost and self._boost_saved is None:
            saved = {}
            for name, value in BOOST_KNOBS.items():
                saved[name] = settings.get(name)
                if saved[name] == value:
                    continue
                settings.set(name, value, source="controller")
                self._record(keyspace="", table="", regime="engine",
                             action="knob", old=repr(saved[name]),
                             new=repr(value), applied=True, reason=name)
                n += 1
            self._boost_saved = saved
        elif not boost and self._boost_saved is not None:
            for name, value in self._boost_saved.items():
                cur = settings.get(name)
                if cur == value:
                    continue
                settings.set(name, value, source="controller")
                self._record(keyspace="", table="", regime="engine",
                             action="knob", old=repr(cur),
                             new=repr(value), applied=True, reason=name)
                n += 1
            self._boost_saved = None
        return n

    def _skip(self, cfs=None, regime: str | None = None,
              reason: str | None = None) -> None:
        with self._lock:
            self.decisions_skipped += 1
        METRICS.incr("controller.skipped")
        if cfs is not None and reason is not None:
            self._record(keyspace=cfs.table.keyspace,
                         table=cfs.table.name, regime=regime,
                         action="strategy", old="", new="",
                         applied=False, reason=reason)

    def _record(self, **entry) -> None:
        """Append one bounded-ledger entry and publish the
        controller.decision diagnostic event (no-op while the bus is
        disabled; the vtable serves the ledger regardless)."""
        with self._lock:
            self._seq += 1
            entry.update(seq=self._seq,
                         at_ms=int(self.wall_clock() * 1000))
            self._ledger.append(entry)
            if entry["applied"]:
                self.decisions_applied += 1
        if entry["applied"]:
            METRICS.incr("controller.decisions")
        from ..service import diagnostics
        diagnostics.publish("controller.decision", actor="controller",
                            **{k: v for k, v in entry.items()
                               if k != "at_ms"})

    # ------------------------------------------------------------- query --

    def decisions(self, limit: int | None = None) -> list[dict]:
        """Ledger entries, oldest first (bounded ring — newest
        LEDGER_CAPACITY kept)."""
        with self._lock:
            rows = [dict(e) for e in self._ledger]
        return rows[-limit:] if limit else rows

    def table_regimes(self) -> dict:
        """{keyspace.table: {regime, candidate, streak, signals}} — the
        status surface."""
        with self._lock:
            return {st["table"]: {
                "regime": st["regime"], "candidate": st["candidate"],
                "streak": st["streak"],
                "signals": dict(st.get("signals") or {})}
                for st in self._state.values()}

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "frozen": self._frozen,
                    "interval_s": self.interval_s, "ticks": self.ticks,
                    "decisions": self.decisions_applied,
                    "skipped": self.decisions_skipped,
                    "ledger_entries": len(self._ledger)}
