"""Build + load the native codec library (g++ -> .so, loaded with ctypes).

Rebuilds automatically when the source is newer than the cached .so.
pybind11 is not available in this image; the C ABI + ctypes keeps the
binding layer dependency-free."""
from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.cpp")
_SRCS = [os.path.join(_DIR, f) for f in ("codec.cpp", "merge.cpp")]
_SO = os.path.join(_DIR, "libcodec.so")
_STAMP = _SO + ".srchash"
_lock = threading.Lock()
_lib = None
_load_error = None  # negative cache: don't re-run g++ per call on failure


def _src_hash() -> str:
    src = b""
    for p in _SRCS:
        with open(p, "rb") as f:
            src += f.read()
    # stamp covers sources AND host (a -march=native binary from a
    # different CPU must never be loaded: SIGILL)
    host = f"{platform.machine()}|{platform.processor()}|{platform.node()}"
    return hashlib.sha256(src + host.encode()).hexdigest()


def _build(h: str) -> None:
    tmp = f"{_SO}.tmp.{os.getpid()}"  # unique per process: no build races
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp] + _SRCS + ["-lz", "-ldl"]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)
    with open(_STAMP + f".{os.getpid()}", "w") as f:
        f.write(h)
    os.replace(_STAMP + f".{os.getpid()}", _STAMP)


def _stale(h: str) -> bool:
    # source-hash stamp, not mtime: a -march=native binary from another
    # machine (or a stale checkout) must never be loaded
    if not os.path.exists(_SO) or not os.path.exists(_STAMP):
        return True
    with open(_STAMP) as f:
        return f.read().strip() != h


def load() -> ctypes.CDLL:
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        raise _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            raise _load_error
        try:
            h = _src_hash()
            if _stale(h):
                _build(h)
        except Exception as e:
            _load_error = RuntimeError(f"native codec build failed: {e}")
            raise _load_error
        lib = ctypes.CDLL(_SO)
        i64 = ctypes.c_int64
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        for fn in ("lz4_compress", "lz4_decompress",
                   "snappy_compress", "snappy_decompress",
                   "zstd_compress", "zstd_decompress"):
            f = getattr(lib, fn)
            f.restype = i64
            f.argtypes = [u8p, i64, u8p, i64]
        for fn in ("lz4_max_compressed", "snappy_max_compressed",
                   "zstd_max_compressed"):
            f = getattr(lib, fn)
            f.restype = i64
            f.argtypes = [i64]
        lib.zstd_available.restype = i64
        lib.zstd_available.argtypes = []
        lib.zstd_set_level.restype = None
        lib.zstd_set_level.argtypes = [ctypes.c_int]
        for fn in ("lz4_compress_batch", "lz4_decompress_batch",
                   "snappy_compress_batch", "snappy_decompress_batch",
                   "zstd_compress_batch", "zstd_decompress_batch"):
            f = getattr(lib, fn)
            f.restype = i64
            f.argtypes = [u8p, i64p, u8p, i64p, i64p, i64]
        u8pp = ctypes.POINTER(u8p)
        for fn in ("lz4_compress_iov", "snappy_compress_iov",
                   "zstd_compress_iov"):
            f = getattr(lib, fn)
            f.restype = i64
            f.argtypes = [u8pp, i64p, u8p, i64p, i64p, i64]
        for fn in ("lz4_decompress_iov", "snappy_decompress_iov",
                   "zstd_decompress_iov"):
            f = getattr(lib, fn)
            f.restype = i64
            f.argtypes = [u8p, i64p, i64p, u8pp, i64p, i64]
        u32p_ = ctypes.POINTER(ctypes.c_uint32)
        lib.segment_pack.restype = i64
        lib.segment_pack.argtypes = [
            i64, u8pp, i64p, i64,            # codec, blocks, lens, nblocks
            u8p, i64,                        # attempt, maxCompressedLen
            i64, i64, u8p,                   # delta_block, lane_width, scratch
            u8p, i64,                        # out, outCap
            i64p, u8p, u32p_]                # outSizes, outRaw, outCrcs
        lib.lanes_unshuffle.restype = None
        lib.lanes_unshuffle.argtypes = [u8p, u8p, i64, i64]
        lib.part_boundaries.restype = i64
        lib.part_boundaries.argtypes = [u32p_, i64, i64, i64p]
        lib.gather_frames.restype = i64
        lib.gather_frames.argtypes = [u8p, i64p, i64p, i64, i64p, u8p]
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.merge_reconcile.restype = i64
        lib.merge_reconcile.argtypes = [
            u32p, i64p, i32p, u8p, i64p, i64p, u8p, i64,  # batch arrays, K
            i64p, i64,                                    # run_starts, n
            i64p, i64, i64,                               # pts, gc, now
            i64p, u8p]                                    # out_idx, out_exp
        _lib = lib
        return _lib
