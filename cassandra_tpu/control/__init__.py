"""Adaptive control plane: the observe/decide/actuate loop closing
ROADMAP item 1 over the PR 13 telemetry substrate. Peer of `service/`
(which observes) and `analysis/` (which checks): this package DECIDES —
and actuates exclusively through the existing hot-reload knob machinery
and strategy re-selection seams, never through side-doors.
"""
from .loop import AdaptiveCompactionController  # noqa: F401
