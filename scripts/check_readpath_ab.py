#!/usr/bin/env python
"""CI check: read-path fast lane A/B — the same fixture workload queried
with CTPU_READ_FASTPATH=0 (naive every-sstable collation) and =1
(timestamp-skip collation + batched multi-partition reads + row cache)
must return IDENTICAL results for every query.

The workload deliberately exercises every case the skip rule must NOT
break: overlapping overwrites across sstables, partition deletions
followed by re-inserts (the skip trigger), row deletions, range
tombstones, TTL cells, static columns, multi-row partitions spread over
4+ flushed sstables plus live memtable writes, and IN (...)
multi-partition reads (the batched gather leg).

Run as a script (exit 1 on divergence) or through pytest
(tests/test_read_fastpath.py imports run_check).
"""
from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_PKS = 12


def _build(session) -> None:
    s = session
    s.execute("CREATE KEYSPACE ab WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ab")
    s.execute("CREATE TABLE t (k int, c int, v text, st text static, "
              "PRIMARY KEY (k, c))")
    s.execute("CREATE TABLE cached (k int, c int, v text, "
              "PRIMARY KEY (k, c)) WITH caching = "
              "{'keys': 'ALL', 'rows_per_partition': 'ALL'}")


def _workload(session, engine) -> None:
    """Four flush rounds + trailing memtable writes."""
    s = session
    t_cfs = engine.store("ab", "t")
    c_cfs = engine.store("ab", "cached")
    # round 0: base rows everywhere
    for k in range(N_PKS):
        s.execute(f"UPDATE t SET st = 's{k}' WHERE k = {k}")
        for c in range(6):
            s.execute(f"INSERT INTO t (k, c, v) VALUES ({k}, {c}, "
                      f"'r0-{k}-{c}')")
            s.execute(f"INSERT INTO cached (k, c, v) VALUES ({k}, {c}, "
                      f"'r0-{k}-{c}')")
    t_cfs.flush()
    c_cfs.flush()
    # round 1: overwrite half the rows, delete rows/partitions/ranges
    for k in range(N_PKS):
        for c in range(0, 6, 2):
            s.execute(f"INSERT INTO t (k, c, v) VALUES ({k}, {c}, "
                      f"'r1-{k}-{c}')")
    s.execute("DELETE FROM t WHERE k = 2")            # partition delete
    s.execute("DELETE FROM t WHERE k = 3 AND c = 1")  # row delete
    s.execute("DELETE FROM t WHERE k = 4 AND c > 3")  # range tombstone
    t_cfs.flush()
    # round 2: re-insert over the deleted partition (newer timestamps),
    # TTL cells (long TTL: liveness must not flip between the A/B legs)
    for c in range(3):
        s.execute(f"INSERT INTO t (k, c, v) VALUES (2, {c}, 'r2-2-{c}')")
    s.execute("INSERT INTO t (k, c, v) VALUES (5, 99, 'ttl') "
              "USING TTL 3600")
    t_cfs.flush()
    # round 3: another full partition supersede (delete + rewrite) —
    # the freshest-wins shape the skip rule fires on
    s.execute("DELETE FROM t WHERE k = 6")
    for c in range(4):
        s.execute(f"INSERT INTO t (k, c, v) VALUES (6, {c}, 'r3-6-{c}')")
    t_cfs.flush()
    # memtable-only tail: never flushed
    s.execute("INSERT INTO t (k, c, v) VALUES (7, 50, 'mem')")
    s.execute("DELETE FROM t WHERE k = 8 AND c = 0")


def _queries() -> list[str]:
    in_list = ", ".join(str(k) for k in range(N_PKS))
    qs = []
    for k in range(N_PKS):
        qs.append(f"SELECT k, c, v, st FROM t WHERE k = {k}")
    qs += [
        f"SELECT k, c, v FROM t WHERE k IN ({in_list})",
        "SELECT k, c, v FROM t WHERE k IN (2, 6, 9) AND c < 3",
        "SELECT k, c, v FROM t WHERE k = 1 LIMIT 3",
        "SELECT k, c, v FROM t WHERE k IN (0, 1, 5) LIMIT 7",
        "SELECT k, c, writetime(v) FROM t WHERE k = 9",
        "SELECT count(*) FROM t WHERE k IN (2, 3, 4)",
        f"SELECT k, c, v FROM cached WHERE k IN ({in_list})",
        "SELECT k, c, v FROM cached WHERE k = 3",
    ]
    return qs


def _run_leg(session, engine, fastpath: bool) -> list:
    os.environ["CTPU_READ_FASTPATH"] = "1" if fastpath else "0"
    # results cached by the OTHER leg must not mask a divergence
    from cassandra_tpu.storage.row_cache import GLOBAL as row_cache
    row_cache.clear()
    out = []
    for q in _queries():
        rs = session.execute(q)
        out.append((q, sorted(map(repr, rs.rows))))
    return out


def run_check(base_dir: str) -> list[str]:
    """Build the fixture once, query it under both modes, return a list
    of human-readable divergences (empty = pass)."""
    from cassandra_tpu.cql import Session
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine

    prev = os.environ.get("CTPU_READ_FASTPATH")
    engine = StorageEngine(os.path.join(base_dir, "ab"), Schema(),
                           commitlog_sync="batch")
    try:
        session = Session(engine)
        _build(session)
        _workload(session, engine)
        assert len(engine.store("ab", "t").live_sstables()) >= 4
        naive = _run_leg(session, engine, fastpath=False)
        fast = _run_leg(session, engine, fastpath=True)
        # second fastpath leg WITHOUT clearing the row cache: cached
        # entries must replay the same results
        os.environ["CTPU_READ_FASTPATH"] = "1"
        cached = []
        for q in _queries():
            cached.append((q, sorted(map(repr,
                                         session.execute(q).rows))))
        diverged = []
        for (q, a), (_, b), (_, c) in zip(naive, fast, cached):
            if a != b:
                diverged.append(f"fastpath diverged on {q!r}:\n"
                                f"  naive:    {a}\n  fastpath: {b}")
            elif a != c:
                diverged.append(f"row-cache replay diverged on {q!r}:\n"
                                f"  naive:  {a}\n  cached: {c}")
        return diverged
    finally:
        if prev is None:
            os.environ.pop("CTPU_READ_FASTPATH", None)
        else:
            os.environ["CTPU_READ_FASTPATH"] = prev
        engine.close()


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="ctpu-readpath-ab-") as d:
        diverged = run_check(d)
    for msg in diverged:
        print(msg, file=sys.stderr)
    if diverged:
        print(f"FAIL: {len(diverged)} diverging quer"
              f"{'y' if len(diverged) == 1 else 'ies'}", file=sys.stderr)
        return 1
    print("readpath A/B: all queries identical (fastpath == naive)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
