"""Diagnostics layer: typed event bus, flight recorder, pipeline
ledger, slow-query phases, audit counters/redaction, Prometheus
exposition edge cases.

Covers the ISSUE 9 acceptance surface: events publish (only) when the
mutable knob is on, the flight recorder dumps a self-contained bundle
on terminal failure policies / quarantine / demand, every hand-rolled
pipeline reports through the unified ledger, and the satellite
hardening (monitor capacity knob, audit bind redaction, exporter
robustness) holds.
"""
import json
import os
import threading
import time

import pytest

from cassandra_tpu.config import Config, Settings
from cassandra_tpu.cql import Session
from cassandra_tpu.schema import COL_ROW_LIVENESS, Schema, make_table
from cassandra_tpu.service import diagnostics
from cassandra_tpu.service.metrics import (GLOBAL as METRICS,
                                           LatencyHistogram,
                                           MetricsRegistry,
                                           prometheus_text)
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.mutation import Mutation
from cassandra_tpu.tools import nodetool
from cassandra_tpu.utils import faultfs, pipeline_ledger


@pytest.fixture(autouse=True)
def _diag_isolation():
    """The bus is process-global: every test starts disabled+empty,
    with every enable demand (anonymous or a leaked engine's)
    withdrawn."""
    diagnostics.GLOBAL.reset()
    yield
    diagnostics.GLOBAL.reset()


def _engine(tmp_path, **cfg):
    settings = Settings(Config.load(cfg)) if cfg else None
    schema = Schema()
    schema.create_keyspace("ks")
    t = make_table("ks", "t", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "text"})
    schema.add_table(t)
    eng = StorageEngine(str(tmp_path / "d"), schema,
                        commitlog_sync="batch", settings=settings)
    return eng, t


def _put(eng, t, pk, c, v, ts):
    m = Mutation(t.id, t.columns["id"].cql_type.serialize(pk))
    ck = t.serialize_clustering([c])
    m.add(ck, COL_ROW_LIVENESS, b"", b"", ts)
    m.add(ck, t.columns["v"].column_id, b"",
          t.columns["v"].cql_type.serialize(v), ts)
    eng.apply(m)


# ------------------------------------------------------------- event bus --


def test_bus_disabled_by_default_and_zero_publish(tmp_path):
    eng, t = _engine(tmp_path)
    try:
        assert not diagnostics.enabled()
        _put(eng, t, 1, 0, "x", 1000)
        eng.store("ks", "t").flush()
        assert diagnostics.GLOBAL.events() == []
    finally:
        eng.close()


def test_knob_enables_bus_and_flush_compaction_events(tmp_path):
    eng, t = _engine(tmp_path)
    try:
        eng.settings.set("diagnostic_events_enabled", True)
        assert diagnostics.enabled()
        cfs = eng.store("ks", "t")
        for gen in range(2):
            for i in range(8):
                _put(eng, t, i, 0, f"g{gen}-{i}", 1000 + gen * 100 + i)
            cfs.flush()
        eng.compactions.major_compaction(cfs)
        types = [e.type for e in diagnostics.GLOBAL.events()]
        # the knob flip itself is an event too (config.reload)
        assert "config.reload" in types
        assert types.count("flush") == 2
        assert "compaction.start" in types
        assert "compaction.finish" in types
        start = next(e for e in diagnostics.GLOBAL.events()
                     if e.type == "compaction.start")
        assert start.fields["keyspace"] == "ks"
        assert start.fields["inputs"] == 2
        # vtable serves the same rows
        rows = list(eng.virtual_tables.get(
            "system_views", "diagnostic_events").rows())
        assert [r["type"] for r in rows] == types
        # nodetool surface
        out = nodetool.diagnostics(eng, limit=100)
        assert out["enabled"] is True
        assert [e["type"] for e in out["events"]] == types
    finally:
        eng.close()


def test_bus_demand_is_per_engine(tmp_path):
    """One co-hosted engine's knob flipping off must not silence the
    bus for a peer whose knob is still on (the mesh-knob demand
    pattern): the bus runs while ANY engine demands it."""
    eng_a, _ = _engine(tmp_path / "a")
    eng_b, _ = _engine(tmp_path / "b")
    try:
        eng_a.settings.set("diagnostic_events_enabled", True)
        assert diagnostics.enabled()
        eng_b.settings.set("diagnostic_events_enabled", True)
        eng_b.settings.set("diagnostic_events_enabled", False)
        assert diagnostics.enabled()          # A still demands
        eng_a.settings.set("diagnostic_events_enabled", False)
        assert not diagnostics.enabled()      # last demand withdrawn
        eng_a.settings.set("diagnostic_events_enabled", True)
    finally:
        eng_a.close()                         # close withdraws A's demand
        assert not diagnostics.enabled()
        eng_b.close()


def test_slow_query_threshold_knob_hot_reloads(tmp_path):
    eng, _ = _engine(tmp_path)
    try:
        eng.settings.set("slow_query_log_timeout", "100ms")
        assert eng.monitor.threshold_ms == 100.0
    finally:
        eng.close()


def test_ring_bounded_per_type():
    diagnostics.GLOBAL.set_enabled(True)
    for i in range(diagnostics.RING_PER_TYPE + 50):
        diagnostics.publish("flush", n=i)
    evs = diagnostics.GLOBAL.events("flush")
    assert len(evs) == diagnostics.RING_PER_TYPE
    assert evs[-1].fields["n"] == diagnostics.RING_PER_TYPE + 49


def test_subscriber_exception_does_not_lose_event():
    diagnostics.GLOBAL.set_enabled(True)

    def bad(_ev):
        raise RuntimeError("boom")
    diagnostics.GLOBAL.subscribe(bad)
    try:
        diagnostics.publish("flush", n=1)
        assert len(diagnostics.GLOBAL.events("flush")) == 1
    finally:
        diagnostics.GLOBAL.unsubscribe(bad)


def test_gossip_and_schema_and_knob_events(tmp_path):
    eng, t = _engine(tmp_path)
    try:
        eng.settings.set("diagnostic_events_enabled", True)
        t2 = make_table("ks", "t2", pk=["id"], cols={"id": "int"})
        eng.add_table(t2)
        eng.settings.set("concurrent_compactors", 2)
        types = [e.type for e in diagnostics.GLOBAL.events()]
        assert "schema.change" in types
        assert types.count("config.reload") == 2
        reloads = diagnostics.GLOBAL.events("config.reload")
        assert reloads[-1].fields["name"] == "concurrent_compactors"
    finally:
        eng.close()


# -------------------------------------------------------- flight recorder --


def test_flight_recorder_dumps_on_stop_policy(tmp_path):
    eng, t = _engine(tmp_path, disk_failure_policy="stop",
                     diagnostic_events_enabled=True)
    try:
        cfs = eng.store("ks", "t")
        for i in range(8):
            _put(eng, t, i, 0, f"a{i}", 1000 + i)
        cfs.flush()
        for i in range(8):
            _put(eng, t, i, 1, f"b{i}", 2000 + i)
        with faultfs.inject("flush.write", "error", times=1):
            with pytest.raises(OSError):
                cfs.flush()
        assert eng.failures.storage_stopped
        assert len(eng.flight_recorder.dumps) == 1
        path = eng.flight_recorder.dumps[0]
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "failure_policy_stop"
        ev_types = [e["type"] for e in bundle["events"]]
        assert "failure.policy" in ev_types
        # the preceding context made it into the black box
        assert "flush" in ev_types[:ev_types.index("failure.policy")]
        assert bundle["final"]["metrics"]["storage.disk_failures"] >= 1
        assert any(p["pool"] == "CompactionExecutor"
                   for p in bundle["final"]["tpstats"])
        assert bundle["failure_state"]["storage_stopped"] is True
        assert any(r["kind"] == "disk" for r in bundle["recent_errors"])
    finally:
        eng.close()


def test_flight_recorder_on_demand_and_status(tmp_path):
    eng, t = _engine(tmp_path)
    try:
        _put(eng, t, 1, 0, "x", 1000)
        out = nodetool.flightrecorder(eng)
        assert os.path.exists(out["bundle"])
        with open(out["bundle"]) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "on_demand"
        assert bundle["final"]["metrics"]["storage.writes"] >= 1
        assert any(s["name"] == "disk_failure_policy"
                   for s in bundle["settings"])
        st = nodetool.flightrecorder(eng, action="status")
        assert out["bundle"] in st["dumps"]
        with pytest.raises(ValueError):
            nodetool.flightrecorder(eng, action="nope")
    finally:
        eng.close()


def test_flight_recorder_trigger_dedup(tmp_path):
    eng, _t = _engine(tmp_path)
    try:
        rec = eng.flight_recorder
        p1 = rec.trigger("failure_policy_stop", error="e1")
        p2 = rec.trigger("failure_policy_stop", error="e2")
        assert p1 is not None and p2 is None   # coalesced in-window
        p3 = rec.trigger("sstable_quarantine", path="x")
        assert p3 is not None                  # different reason dumps
    finally:
        eng.close()


def test_stop_commit_policy_dumps(tmp_path):
    eng, t = _engine(tmp_path, commit_failure_policy="stop_commit")
    try:
        _put(eng, t, 1, 0, "x", 1000)
        eng.failures.handle_commit(OSError(5, "sync eio"))
        assert eng.failures.commits_stopped
        assert any("stop_commit" in p for p in eng.flight_recorder.dumps)
    finally:
        eng.close()


# -------------------------------------------------------- pipeline ledger --


def test_stage_accounting_primitives():
    led = pipeline_ledger.ledger("compaction")
    st = led.stage("io_write")
    before = st.snapshot()
    st.add_busy(0.5)
    st.add_stall(0.25)
    st.add_idle(0.125)
    st.add_items(3, 4096)
    st.note_queue(7)
    st.note_queue(2)   # lower than hwm: ignored
    s = st.snapshot()
    # snapshot() rounds to 6 digits: with prior accumulation from the
    # rest of the suite on this process-global ledger, rounded(a + 0.5)
    # can sit one ulp below rounded(a) + 0.5 — compare with the
    # rounding tolerance
    assert s["busy_s"] >= before["busy_s"] + 0.5 - 1e-6
    assert s["stall_s"] >= before["stall_s"] + 0.25 - 1e-6
    assert s["idle_s"] >= before["idle_s"] + 0.125 - 1e-6
    assert s["items"] == before["items"] + 3
    assert s["bytes"] == before["bytes"] + 4096
    assert s["queue_hwm"] >= 7
    with st.busy():
        time.sleep(0.01)
    assert st.snapshot()["busy_s"] >= s["busy_s"] + 0.009
    # same (pipeline, stage) resolves to the same object
    assert pipeline_ledger.ledger("compaction").stage("io_write") is st


def test_flush_populates_ledger_and_vtable(tmp_path):
    eng, t = _engine(tmp_path)
    try:
        pipeline_ledger.reset_all()
        cfs = eng.store("ks", "t")
        for i in range(64):
            _put(eng, t, i, 0, "v" * 64, 1000 + i)
        cfs.flush()
        snap = pipeline_ledger.snapshot_all()
        assert snap["flush"]["io_write"]["bytes"] > 0
        assert snap["flush"]["io_write"]["items"] >= 1
        assert snap["flush"]["compress"]["busy_s"] > 0
        # the fast-path flush ran the drain stage
        assert snap["flush"]["drain"]["items"] >= 1
        # gauges surface through the registry
        reg = METRICS.snapshot()
        assert reg["pipeline.flush.io_write.bytes"] == \
            snap["flush"]["io_write"]["bytes"]
        # vtable + nodetool agree
        rows = {(r["pipeline"], r["stage"]): r
                for r in eng.virtual_tables.get(
                    "system_views", "pipelines").rows()}
        assert rows[("flush", "io_write")]["bytes"] == \
            snap["flush"]["io_write"]["bytes"]
        assert nodetool.pipelinestats(eng)["flush"]["io_write"][
            "bytes"] == snap["flush"]["io_write"]["bytes"]
    finally:
        eng.close()


def test_compaction_ledger_matches_profile(tmp_path):
    """The ledger's write-leg busy seconds and the task profile's phase
    split are the same measurements — they must reconcile exactly."""
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore
    table = make_table("b", "t", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "text"})
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    vcol = table.columns["v"].column_id
    for gen in range(2):
        for i in range(512):
            m = Mutation(table.id, table.serialize_partition_key([i]))
            m.add(table.serialize_clustering([0]), vcol, b"",
                  f"g{gen}-{i}".encode(), 1000 + gen * 10000 + i)
            cfs.apply(m)
        cfs.flush()
    pipeline_ledger.reset_all()
    task = CompactionTask(cfs, cfs.tracker.view(), mesh_devices=0)
    task.execute()
    led = pipeline_ledger.ledger("compaction").snapshot()
    for stage in ("compress", "io_write"):
        prof_s = task.profile.get(stage, 0.0)
        assert led[stage]["busy_s"] == pytest.approx(prof_s, abs=1e-6)
    assert led["io_write"]["bytes"] > 0
    for s in cfs.live_sstables():
        s.close()


def test_mesh_ledger_stages(tmp_path):
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore
    table = make_table("b", "tm", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "text"})
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    vcol = table.columns["v"].column_id
    for gen in range(2):
        for i in range(512):
            m = Mutation(table.id, table.serialize_partition_key([i]))
            m.add(table.serialize_clustering([0]), vcol, b"",
                  f"g{gen}-{i}".encode(), 1000 + gen * 10000 + i)
            cfs.apply(m)
        cfs.flush()
    pipeline_ledger.reset_all()
    task = CompactionTask(cfs, cfs.tracker.view(), mesh_devices=2)
    task.execute()
    led = pipeline_ledger.ledger("mesh").snapshot()
    assert led["decode"]["items"] >= 1        # shards decoded
    assert led["merge"]["items"] >= 1         # cells merged
    assert led["merge"]["busy_s"] > 0
    for s in cfs.live_sstables():
        s.close()


def test_transport_dispatch_ledger(tmp_path):
    from cassandra_tpu.transport.server import CQLServer
    eng, t = _engine(tmp_path)
    pipeline_ledger.reset_all()
    srv = CQLServer(eng)
    try:
        import socket
        import struct

        from cassandra_tpu.transport.frame import (encode_envelope,
                                                   _read_string)
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        body = struct.pack(">H", 1) + \
            struct.pack(">H", len("CQL_VERSION")) + b"CQL_VERSION" + \
            struct.pack(">H", len("3.4.5")) + b"3.4.5"
        s.sendall(encode_envelope(0x04, 0, 0x01, body))   # STARTUP
        s.recv(4096)
        q = b"SELECT * FROM system.local"
        qbody = struct.pack(">i", len(q)) + q + \
            struct.pack(">H", 1) + b"\x00"
        s.sendall(encode_envelope(0x04, 1, 0x07, qbody))  # QUERY
        s.recv(65536)
        s.close()
        snap = pipeline_ledger.ledger("transport").snapshot()
        assert snap["dispatch"]["items"] >= 1
        assert snap["dispatch"]["busy_s"] > 0
    finally:
        srv.close()
        eng.close()


# --------------------------------------------------- slow-query satellite --


def test_monitor_capacity_knob_and_phases(tmp_path):
    eng, _t = _engine(tmp_path)
    try:
        assert eng.monitor.capacity == \
            eng.settings.get("slow_query_log_entries")
        eng.settings.set("slow_query_log_entries", 3)
        assert eng.monitor.capacity == 3
        eng.monitor.threshold_ms = 0.0
        for i in range(6):
            eng.monitor.record(f"q{i}", 0.01, "ks",
                               phases={"parse": 0.001,
                                       "execute": 0.008,
                                       "serialize": 0.001})
        entries = eng.monitor.entries()
        assert len(entries) == 3                  # shrunk ring holds 3
        assert entries[-1]["query"] == "q5"       # newest survive
        assert entries[-1]["parse_ms"] == 1.0
        assert entries[-1]["execute_ms"] == 8.0
        assert entries[-1]["serialize_ms"] == 1.0
    finally:
        eng.close()


def test_slow_query_phase_breakdown_end_to_end(tmp_path):
    eng, _t = _engine(tmp_path)
    try:
        eng.monitor.threshold_ms = 0.0
        s = Session(eng)
        s.execute("CREATE TABLE ks.kv (k int PRIMARY KEY, v text)")
        s.execute("INSERT INTO ks.kv (k, v) VALUES (1, 'x')")
        s.execute("SELECT v FROM ks.kv WHERE k = 1")
        entry = eng.monitor.entries()[-1]
        assert entry["query"].startswith("SELECT")
        # the phases reconcile with (never exceed) the total
        assert 0.0 <= entry["parse_ms"] <= entry["duration_ms"]
        assert 0.0 < entry["execute_ms"] <= entry["duration_ms"]
        rows = list(eng.virtual_tables.get(
            "system_views", "slow_queries").rows())
        assert rows[-1]["execute_ms"] == entry["execute_ms"]
        assert rows[-1]["parse_ms"] == entry["parse_ms"]
    finally:
        eng.close()


# --------------------------------------------------------- audit satellite --


def test_audit_counters_and_bind_redaction(tmp_path):
    from cassandra_tpu.service.audit import AuditLog
    path = str(tmp_path / "audit.jsonl")
    log = AuditLog(path)
    before_rec = METRICS.counter("audit.records")
    before_drop = METRICS.counter("audit.dropped")
    # literal passwords scrub (pre-existing) and binds redact (new)
    log.log("RoleStatement",
            "CREATE ROLE r WITH password = 'hunter2'", "admin", None)
    log.log("RoleStatement",
            "ALTER ROLE r WITH password = ?", "admin", None,
            params=[b"hunter2"])
    log.log("SelectStatement", "SELECT * FROM t WHERE k = ?",
            None, "ks", params=[b"\x01"])
    log.close()
    recs = [json.loads(line) for line in open(path)]
    assert "hunter2" not in recs[0]["query"]
    assert recs[1]["params"] == ["***"]          # bind value redacted
    assert "68756e74657232" not in json.dumps(recs[1])   # hex leak
    assert recs[2]["params"] == ["01"]           # normal binds intact
    assert METRICS.counter("audit.records") == before_rec + 3
    # wedged (closed) file: dropped counts, the request survives
    log.log("SelectStatement", "SELECT 1", None, None)
    assert METRICS.counter("audit.dropped") == before_drop + 1
    assert METRICS.counter("audit.records") == before_rec + 3


# ------------------------------------------------- exporter edge cases --


def test_prometheus_raising_gauge_skipped():
    reg = MetricsRegistry()
    reg.incr("cql.request")
    reg.register_gauge("storage.good_gauge", lambda: 7.0)
    reg.register_gauge("storage.bad_gauge",
                       lambda: (_ for _ in ()).throw(RuntimeError()))
    snap = reg.snapshot()
    assert snap["storage.good_gauge"] == 7.0
    assert "storage.bad_gauge" not in snap
    text = prometheus_text(reg)
    assert "ctpu_storage_good_gauge 7.0" in text
    assert "bad_gauge" not in text
    assert "ctpu_cql_request 1" in text


def test_prometheus_name_sanitization_no_injection():
    """A hostile registered name cannot inject lines/labels into the
    exposition: every exported name collapses to [a-zA-Z0-9_]."""
    reg = MetricsRegistry()
    hostile = 'evil.name"} 1\nfake_metric{x="'
    reg.incr(hostile)
    reg.register_gauge('g.a"b\nc\\d', lambda: 1.0)
    text = prometheus_text(reg)
    for line in text.splitlines():
        name = line.split("{")[0].split(" ")[1] \
            if line.startswith("#") else line.split("{")[0].split(" ")[0]
        assert all(c.isalnum() or c == "_" for c in name), line
    assert '"} 1' not in text.replace('quantile="', "")
    # and exposition stays line-parseable: name SP value
    for line in text.splitlines():
        if not line.startswith("#"):
            assert len(line.split()) == 2


def test_escape_label_value():
    from cassandra_tpu.service.metrics import _escape_label
    assert _escape_label('a"b') == 'a\\"b'
    assert _escape_label("a\\b") == "a\\\\b"
    assert _escape_label("a\nb") == "a\\nb"
    # order: backslashes first, so escapes survive escaping
    assert _escape_label('\\"') == '\\\\\\"'


def test_histogram_summary_under_concurrent_updates():
    """A scrape racing a recording storm must stay internally
    consistent: count monotone, total >= count (each sample >= 1us
    here), percentiles within the recorded range, no exception."""
    h = LatencyHistogram(window_s=60.0)
    stop = threading.Event()
    errs = []

    def hammer():
        i = 0
        try:
            while not stop.is_set():
                h.update_us(1 + (i % 1000))
                i += 1
        except Exception as e:   # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    last_count = 0
    try:
        for _ in range(200):
            s = h.summary()
            assert s["count"] >= last_count
            last_count = s["count"]
            if s["count"]:
                assert s["total_us"] >= s["count"]
                assert 0 < s["p50_us"] <= s["max_us"] * 2
                assert s["p50_us"] <= s["p99_us"]
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errs


# ------------------------------------------------------ trace coverage --


def test_mesh_read_shards_traced(tmp_path):
    from cassandra_tpu.parallel import fanout
    from cassandra_tpu.service import tracing
    from cassandra_tpu.storage.table import ColumnFamilyStore
    table = make_table("b", "tr", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "text"})
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    vcol = table.columns["v"].column_id
    for gen in range(2):
        for i in range(64):
            m = Mutation(table.id, table.serialize_partition_key([i]))
            m.add(table.serialize_clustering([0]), vcol, b"",
                  f"g{gen}-{i}".encode(), 1000 + gen * 10000 + i)
            cfs.apply(m)
        cfs.flush()
    fanout.configure(2)
    try:
        pks = [table.serialize_partition_key([i]) for i in range(32)]
        st = tracing.begin(request="mesh batched read")
        try:
            cfs.read_partitions(pks)
        finally:
            tracing.end()
        activities = [a for _us, _src, a in st.events]
        dispatched = [a for a in activities
                      if a.startswith("Mesh read shard")
                      and "dispatched" in a]
        completed = [a for a in activities
                     if a.startswith("Mesh read shard")
                     and "complete" in a]
        assert len(dispatched) >= 2
        assert len(completed) == len(dispatched)
    finally:
        fanout.reset()
        for s in cfs.live_sstables():
            s.close()


def test_compress_pool_jobs_traced(tmp_path):
    """A traced statement that pays an inline flush sees the pool's
    pack jobs on its timeline (submit on the producer, packed on the
    ordered completion thread)."""
    from cassandra_tpu.service import tracing
    from cassandra_tpu.storage.table import ColumnFamilyStore
    table = make_table("b", "tp", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "text"})
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    vcol = table.columns["v"].column_id
    for i in range(256):
        m = Mutation(table.id, table.serialize_partition_key([i]))
        m.add(table.serialize_clustering([0]), vcol, b"",
              ("v" * 200).encode(), 1000 + i)
        cfs.apply(m)
    st = tracing.begin(request="traced flush")
    try:
        cfs.flush()
    finally:
        tracing.end()
    activities = [a for _us, _src, a in st.events]
    assert any(a.startswith("Compress pool: segment")
               and "submitted" in a for a in activities)
    assert any(a.startswith("Compress pool: segment")
               and "packed" in a for a in activities)
    for s in cfs.live_sstables():
        s.close()


def test_mesh_compaction_shards_traced(tmp_path):
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.service import tracing
    from cassandra_tpu.storage.table import ColumnFamilyStore
    table = make_table("b", "tc", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "text"})
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    vcol = table.columns["v"].column_id
    for gen in range(2):
        for i in range(256):
            m = Mutation(table.id, table.serialize_partition_key([i]))
            m.add(table.serialize_clustering([0]), vcol, b"",
                  f"g{gen}-{i}".encode(), 1000 + gen * 10000 + i)
            cfs.apply(m)
        cfs.flush()
    st = tracing.begin(request="traced mesh compaction")
    try:
        CompactionTask(cfs, cfs.tracker.view(), mesh_devices=2).execute()
    finally:
        tracing.end()
    activities = [a for _us, _src, a in st.events]
    assert any(a.startswith("Mesh shard") and "dispatched" in a
               for a in activities)
    assert any(a.startswith("Mesh shard") and "complete" in a
               for a in activities)
    for s in cfs.live_sstables():
        s.close()


# ------------------------------------------------------- quarantine path --


def test_quarantine_publishes_and_dumps(tmp_path):
    eng, t = _engine(tmp_path, diagnostic_events_enabled=True)
    try:
        cfs = eng.store("ks", "t")
        for i in range(16):
            _put(eng, t, i, 0, f"v{i}", 1000 + i)
        cfs.flush()
        sst = cfs.live_sstables()[0]
        data = sst.desc.path("Data.db")
        with open(data, "r+b") as f:
            f.seek(50)
            b = f.read(1)
            f.seek(50)
            f.write(bytes([b[0] ^ 0xFF]))
        from cassandra_tpu.storage import chunk_cache
        chunk_cache.GLOBAL.clear()
        try:
            cfs.read_partition(t.columns["id"].cql_type.serialize(0))
        except Exception:
            pass
        if not cfs.quarantined:
            pytest.skip("bit flip landed in slack; no quarantine")
        evs = diagnostics.GLOBAL.events("sstable.quarantine")
        assert len(evs) == 1
        assert evs[0].fields["keyspace"] == "ks"
        assert any("quarantine" in p for p in eng.flight_recorder.dumps)
    finally:
        eng.close()
