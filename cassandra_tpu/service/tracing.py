"""Request tracing: per-query event timelines.

Reference counterpart: tracing/Tracing.java:52 — a session id propagated
through stages; events land in system_traces and cqlsh's TRACING ON
renders them. Here a contextvar carries the active trace; subsystems call
trace("..."); Session.execute(..., trace=True) returns the events on the
result set.
"""
from __future__ import annotations

import contextvars
import time
import uuid as uuid_mod
from dataclasses import dataclass, field

_current: contextvars.ContextVar = contextvars.ContextVar(
    "trace_state", default=None)


@dataclass
class TraceState:
    session_id: uuid_mod.UUID = field(default_factory=uuid_mod.uuid4)
    started: float = field(default_factory=time.perf_counter)
    events: list = field(default_factory=list)

    def add(self, activity: str, source: str = "local") -> None:
        self.events.append(
            (round((time.perf_counter() - self.started) * 1e6), source,
             activity))


def begin() -> TraceState:
    st = TraceState()
    _current.set(st)
    return st


def end() -> None:
    _current.set(None)


def trace(activity: str, source: str = "local") -> None:
    st = _current.get()
    if st is not None:
        st.add(activity, source)


def active() -> TraceState | None:
    return _current.get()
