"""Range tombstones: clustering-range deletion markers.

Reference counterpart: db/RangeTombstone.java + db/RangeTombstoneList.java
(normalized slice list, newest-wins on overlap), db/ClusteringBound.java
(inclusive/exclusive prefix bounds), db/rows/RangeTombstoneMarker.java
(merge participation).

Columnar formulation: a range tombstone is ONE cell — column sentinel
COL_RANGE_TOMB, ck frame = the start bound's composite (so its identity
lanes position it inside its partition), cell path = the encoded
(start-kind, end bound, end-kind) suffix (so distinct ranges are distinct
cells and an identical re-write reconciles newest-wins through the
ordinary cell machinery). Coverage is evaluated per partition against the
full byte-comparable clustering composites, which the cells already carry
in their payload frames — the marker's position in the sorted stream is
NOT load-bearing, so prefix-lane hash ordering cannot corrupt range
semantics.

Bound semantics on composites (composites are self-terminating, so a
byte-prefix relationship == a clustering-prefix relationship):
  start (P, inclusive): covers rows R == P, R extending P, and R > P
  start (P, exclusive): covers only R > P that do NOT extend P
  end   (P, inclusive): covers rows R == P, R extending P, and R < P
  end   (P, exclusive): covers only R < P that do NOT extend P
An open bound is P = b"" inclusive. Static cells (ck frame == b"") are
never covered — range tombstones do not delete the static row
(reference: Clustering.STATIC_CLUSTERING sorts outside all bounds).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..utils import varint as vi


@dataclass(frozen=True)
class Slice:
    start: bytes          # composite of the start bound (b"" = open)
    start_incl: bool
    end: bytes            # composite of the end bound (b"" = open)
    end_incl: bool
    ts: int               # deletion timestamp (markedForDeleteAt)
    ldt: int              # local deletion time (purge clock)

    # ---------------------------------------------------------- encoding --

    def encode_path(self) -> bytes:
        """The cell-path payload: kinds byte + end bound."""
        kinds = (1 if self.start_incl else 0) | \
            (2 if self.end_incl else 0)
        out = bytearray([kinds])
        vi.write_unsigned_vint(len(self.end), out)
        out += self.end
        return bytes(out)

    @classmethod
    def from_cell(cls, ck: bytes, path: bytes, ts: int,
                  ldt: int) -> "Slice":
        kinds = path[0]
        n, pos = vi.read_unsigned_vint(path, 1)
        end = bytes(path[pos:pos + n])
        return cls(ck, bool(kinds & 1), end, bool(kinds & 2), ts, ldt)

    # ---------------------------------------------------------- coverage --

    @staticmethod
    def _start_covers(p: bytes, incl: bool, r: bytes) -> bool:
        if r.startswith(p):           # equal or clustering-prefix extension
            return incl
        return r > p

    @staticmethod
    def _end_covers(p: bytes, incl: bool, r: bytes) -> bool:
        if not p:                     # open end
            return True
        if r.startswith(p):
            return incl
        return r < p

    def covers_row(self, r: bytes) -> bool:
        """Does this slice delete row with full clustering composite r?
        (r == b'' — the static row — is never covered.)"""
        if not r:
            return False
        return self._start_covers(self.start, self.start_incl, r) and \
            self._end_covers(self.end, self.end_incl, r)

    # start_a positioned at-or-before start_b?
    @staticmethod
    def _start_le(pa: bytes, ia: bool, pb: bytes, ib: bool) -> bool:
        if pa == pb:
            return ia or not ib
        if pb.startswith(pa):   # a's bound is a prefix of b's
            return ia           # inclusive prefix start precedes extensions
        if pa.startswith(pb):
            return not ib       # b inclusive -> b precedes everything a-ish
        return pa < pb

    @staticmethod
    def _end_ge(pa: bytes, ia: bool, pb: bytes, ib: bool) -> bool:
        if pa == b"" != pb:
            return True
        if pb == b"" != pa:
            return False
        if pa == pb:
            return ia or not ib
        if pb.startswith(pa):
            return ia           # inclusive prefix end follows extensions
        if pa.startswith(pb):
            return not ib
        return pa > pb

    def contains(self, other: "Slice") -> bool:
        """Does this slice's range fully cover other's range?"""
        return self._start_le(self.start, self.start_incl,
                              other.start, other.start_incl) and \
            self._end_ge(self.end, self.end_incl,
                         other.end, other.end_incl)


def covering_ts(slices: list[Slice], r: bytes) -> int:
    """Max deletion timestamp over the slices covering row r;
    NO_TIMESTAMP (int64 min) when none do."""
    best = -(1 << 63)
    for s in slices:
        if s.ts > best and s.covers_row(r):
            best = s.ts
    return best
