"""cassandra-stress analog: write/read/mixed workloads against a Session.

Reference counterpart: tools/stress/ (Stress.java; `write n=1000000`,
`read n=...`) and CompactionStress.java (offline write + compact — that
path is bench.py). Usable as a library (tests/benchmarks) or CLI:
`python -m cassandra_tpu.tools.stress write -n 100000`.
"""
from __future__ import annotations

import argparse
import json
import random
import time


DDL = ("CREATE TABLE IF NOT EXISTS stress.standard1 "
       "(key int PRIMARY KEY, c0 blob, c1 blob, c2 blob, c3 blob)")


def setup(session):
    session.execute("CREATE KEYSPACE IF NOT EXISTS stress WITH replication "
                    "= {'class': 'SimpleStrategy', 'replication_factor': 1}")
    try:
        session.execute(DDL)
    except Exception:
        pass


def write(session, n: int, value_bytes: int = 34, seed: int = 1) -> dict:
    setup(session)
    rng = random.Random(seed)
    qid = session.prepare("INSERT INTO stress.standard1 "
                          "(key, c0, c1, c2, c3) VALUES (?, ?, ?, ?, ?)")
    t0 = time.time()
    for i in range(n):
        vals = [rng.randbytes(value_bytes) for _ in range(4)]
        session.execute_prepared(qid, (i, *vals))
    dt = time.time() - t0
    return {"op": "write", "n": n, "seconds": round(dt, 3),
            "ops_s": round(n / dt, 1)}


def read(session, n: int, keys: int | None = None, seed: int = 2) -> dict:
    rng = random.Random(seed)
    keys = keys or n
    qid = session.prepare("SELECT * FROM stress.standard1 WHERE key = ?")
    t0 = time.time()
    hits = 0
    for _ in range(n):
        rs = session.execute_prepared(qid, (rng.randrange(keys),))
        hits += bool(rs.rows)
    dt = time.time() - t0
    return {"op": "read", "n": n, "hits": hits, "seconds": round(dt, 3),
            "ops_s": round(n / dt, 1)}


def mixed(session, n: int, write_ratio: float = 0.5, seed: int = 3) -> dict:
    setup(session)
    rng = random.Random(seed)
    wq = session.prepare("INSERT INTO stress.standard1 "
                         "(key, c0, c1, c2, c3) VALUES (?, ?, ?, ?, ?)")
    rq = session.prepare("SELECT * FROM stress.standard1 WHERE key = ?")
    t0 = time.time()
    for i in range(n):
        if rng.random() < write_ratio:
            session.execute_prepared(
                wq, (rng.randrange(n),
                     *[rng.randbytes(34) for _ in range(4)]))
        else:
            session.execute_prepared(rq, (rng.randrange(n),))
    dt = time.time() - t0
    return {"op": "mixed", "n": n, "seconds": round(dt, 3),
            "ops_s": round(n / dt, 1)}


def main(argv=None):
    p = argparse.ArgumentParser(prog="stress")
    p.add_argument("op", choices=["write", "read", "mixed"])
    p.add_argument("-n", type=int, default=10000)
    p.add_argument("--data", default=None)
    args = p.parse_args(argv)

    import tempfile

    from ..cql import Session
    from ..schema import Schema
    from ..storage.engine import StorageEngine
    data = args.data or tempfile.mkdtemp(prefix="ctpu-stress-")
    engine = StorageEngine(data, Schema())
    session = Session(engine)
    if args.op == "read":
        print(json.dumps(write(session, args.n)))  # preload
    print(json.dumps(globals()[args.op](session, args.n)))
    engine.close()


if __name__ == "__main__":
    main()
